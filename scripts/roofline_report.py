"""Measured roofline: CostBook compiled cost x StageClock device time.

docs/ROOFLINE.md's original tables were hand-derived FLOP/byte counts
divided by spec-sheet peaks.  This script replaces the estimate half
with measurement: it runs the served path (GameRole over a benchmark
world, simulated sessions, NF_STAGE_TIMING=1 so each stage blocks on its
device work) and folds the CostBook's per-entry `cost_analysis()`
FLOPs/bytes against the StageClock's per-stage seconds into
achieved-vs-peak fractions per stage (telemetry/costbook.roofline_fold).

The schema is platform-agnostic; on the CPU backend the peak
denominators are the PEAKS table's provisional placeholders and the
output is marked `"provisional": true` — the achieved numerators are
real either way.

Usage:
    NF_STAGE_TIMING=1 python scripts/roofline_report.py \
        [--entities 20000] [--sessions 32] [--ticks 120] [--round r08]

Writes bench_runs/roofline_<round>.json (stdout gets the same JSON).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# honest device seconds are the whole point: force the stage clock's
# block_until_ready spans on before any role code reads the env
os.environ["NF_STAGE_TIMING"] = "1"


def run(args) -> dict:
    import jax

    from noahgameframe_tpu.core.datatypes import next_pow2
    from noahgameframe_tpu.game import build_benchmark_world
    from noahgameframe_tpu.net.roles.base import RoleConfig
    from noahgameframe_tpu.net.roles.game import GameRole, Session
    from noahgameframe_tpu.net.wire import Ident, ident_key
    from noahgameframe_tpu.telemetry.costbook import roofline_fold
    from noahgameframe_tpu.utils.platform import init_compile_cache

    init_compile_cache()
    world = build_benchmark_world(
        args.entities, combat=True, seed=args.seed,
        player_capacity=next_pow2(args.sessions + 8, lo=64),
    )
    role = GameRole(
        RoleConfig(6, 0, "RooflineGame", "127.0.0.1", 0),
        backend="py", world=world, cross_server_sync=False,
        interest_radius=args.interest_radius,
    )
    role.server.send_raw = lambda conn_id, msg_id, body: True
    for i in range(args.sessions):
        ident = Ident(svrid=99, index=i + 1)
        sess = Session(ident=ident, conn_id=1000 + (i % 8),
                       account=f"bot{i}")
        sess.guid = role.kernel.create_object(
            "Player", {"Name": f"Bot{i}"}, scene=1, group=0)
        role.sessions[ident_key(ident)] = sess
        role._guid_session[sess.guid] = ident_key(ident)

    dt = world.config.dt * 1.0001
    now = 1000.0
    for _ in range(3):  # warmup: compile + first flush
        now += dt
        role.execute(now)
    jax.block_until_ready(role.kernel.state.classes["NPC"].i32)
    for _ in range(args.ticks):
        now += dt
        role.execute(now)
    jax.block_until_ready(role.kernel.state.classes["NPC"].i32)

    book = role.kernel.costbook
    hbm = book.hbm_sample()
    fold = roofline_fold(book, role.pipeline_stats())
    return {
        "metric": "roofline_frac_of_peak",
        "entities": args.entities,
        "sessions": args.sessions,
        "ticks": args.ticks,
        "seed": args.seed,
        "interest_radius": args.interest_radius,
        "stage_timing": True,
        "device": str(jax.devices()[0]),
        "hbm": hbm,
        "compile_ms": round(book.compile_s_total * 1e3, 1),
        "compiles": book.total_compiles,
        "recompiles": book.total_recompiles,
        "roofline": fold,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--entities", type=int, default=20_000)
    ap.add_argument("--sessions", type=int, default=32)
    ap.add_argument("--ticks", type=int, default=120)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--interest-radius", type=float, default=16.0)
    ap.add_argument("--round", default="r08",
                    help="bench round tag for the output filename")
    ap.add_argument("--out-dir", default=os.path.join(
        os.path.dirname(__file__), "..", "bench_runs"))
    args = ap.parse_args()

    out = run(args)
    path = os.path.join(args.out_dir, f"roofline_{args.round}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    print(f"# wrote {os.path.normpath(path)}", file=sys.stderr)


if __name__ == "__main__":
    main()
