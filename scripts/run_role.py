#!/usr/bin/env python
"""Run one server role as a standalone process (NFPluginLoader equivalent).

The reference launches each role as `NFPluginLoader Server=GameServer ID=6`
reading Server.xml (`_Out/Tester/rund_*.sh`); here:

    python scripts/run_role.py --role master --id 1 --server-xml cluster.xml
    python scripts/run_role.py --role game --id 6 --server-xml cluster.xml

Server.xml lists every instance in the cluster; each process picks its own
row by (role, id) and derives its upstream targets from the others
(login/world dial the master; proxy/game dial the world).
"""

from __future__ import annotations

import argparse
import atexit
import faulthandler
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from noahgameframe_tpu.net.defines import ServerType  # noqa: E402
from noahgameframe_tpu.net.roles import (  # noqa: E402
    GameRole,
    LoginRole,
    MasterRole,
    ProxyRole,
    WorldRole,
    load_server_xml,
)

ROLE_CLASSES = {
    "master": (MasterRole, int(ServerType.MASTER), None),
    "login": (LoginRole, int(ServerType.LOGIN), int(ServerType.MASTER)),
    "world": (WorldRole, int(ServerType.WORLD), int(ServerType.MASTER)),
    "proxy": (ProxyRole, int(ServerType.PROXY), int(ServerType.WORLD)),
    "game": (GameRole, int(ServerType.GAME), int(ServerType.WORLD)),
}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--role", required=True, choices=sorted(ROLE_CLASSES))
    ap.add_argument("--id", type=int, required=True, help="server id in Server.xml")
    ap.add_argument("--server-xml", required=True, type=Path)
    ap.add_argument("--http-port", type=int, default=None,
                    help="HTTP port: the master serves /json + /metrics "
                         "on it; every other role serves /metrics")
    ap.add_argument("--tick-sleep", type=float, default=0.001,
                    help="main-loop sleep (reference: 1 ms)")
    ap.add_argument("--crash-log-dir", type=Path, default=Path("crashlogs"),
                    help="where crash tracebacks are written")
    ap.add_argument(
        "--platform", choices=("default", "cpu"), default="default",
        help="cpu: force the CPU jax backend for this role process "
             "(control-plane roles and tests; the sitecustomize "
             "overrides JAX_PLATFORMS env at startup)",
    )
    ap.add_argument("--checkpoint-dir", type=Path, default=None,
                    help="game role: directory for periodic atomic "
                         "whole-world checkpoints")
    ap.add_argument("--checkpoint-seconds", type=float, default=30.0,
                    help="game role: seconds between checkpoints")
    ap.add_argument("--resume", action="store_true",
                    help="game role: restore the latest checkpoint from "
                         "--checkpoint-dir before serving")
    ap.add_argument("--journal", type=Path, default=None,
                    help="game role: record every host->device input "
                         "(commands, migrations, tick digests) to this "
                         "flight-recorder directory")
    ap.add_argument("--journal-segment-bytes", type=int, default=1 << 20,
                    help="journal segment rotation threshold")
    ap.add_argument("--replay", type=Path, default=None,
                    help="game role: do not serve; rebuild device state "
                         "offline from --checkpoint-dir + this journal, "
                         "verify every per-tick digest, exit 0 iff "
                         "bit-identical")
    args = ap.parse_args()
    if args.platform == "cpu":
        from noahgameframe_tpu.utils.platform import force_cpu

        force_cpu()

    # crash capture: the reference installs a minidump handler around its
    # main loop (NFPluginLoader.cpp:42-69); the Python equivalent dumps
    # every thread's traceback to a per-process crash file on SIGSEGV/
    # SIGFPE/SIGABRT/SIGBUS and on hard faults in native extensions
    args.crash_log_dir.mkdir(parents=True, exist_ok=True)
    crash_path = args.crash_log_dir / f"{args.role}_{args.id}_{os.getpid()}.crash"
    crash_file = open(crash_path, "w")  # noqa: SIM115 — must outlive main
    faulthandler.enable(file=crash_file, all_threads=True)

    def _tidy_crash_file() -> None:
        # keep only real fault dumps; a clean exit leaves the file empty
        try:
            crash_file.flush()
            if crash_path.stat().st_size == 0:
                crash_path.unlink()
        except OSError:
            pass

    atexit.register(_tidy_crash_file)

    if args.replay is not None:
        if args.role != "game":
            print("--replay is a game-role mode", file=sys.stderr)
            return 2
        from noahgameframe_tpu.replay import replay_journal

        report = replay_journal(args.replay, checkpoint=args.checkpoint_dir)
        print(report.summary(), flush=True)
        return 0 if report.ok else 1

    cls, stype, upstream_type = ROLE_CLASSES[args.role]
    rows = load_server_xml(args.server_xml)
    mine = [r for r in rows if r.server_type == stype and r.server_id == args.id]
    if not mine:
        print(f"no <Server> row with Type={args.role} ID={args.id}", file=sys.stderr)
        return 2
    config = mine[0]
    if upstream_type is not None:
        config.targets = [r for r in rows if r.server_type == upstream_type]

    kwargs = {}
    if args.role == "master" and args.http_port is not None:
        kwargs["http_port"] = args.http_port
    if args.role == "game" and args.checkpoint_dir is not None:
        kwargs["checkpoint_dir"] = args.checkpoint_dir
        kwargs["checkpoint_seconds"] = args.checkpoint_seconds
        kwargs["resume"] = args.resume
    if args.role == "game" and args.journal is not None:
        kwargs["journal_dir"] = args.journal
        kwargs["journal_segment_bytes"] = args.journal_segment_bytes
    role = cls(config, **kwargs)
    if args.role != "master" and args.http_port is not None:
        h = role.serve_metrics(args.http_port)
        print(f"{args.role} id={config.server_id} /metrics on "
              f"{config.ip}:{h.port}", flush=True)
    print(f"{args.role} id={config.server_id} listening on "
          f"{config.ip}:{config.port}", flush=True)
    try:
        while True:
            # frame percentiles ride the 10 s report's ext map to the
            # master dashboard (the reference reports raw counts only)
            with role.metrics.frame():
                role.execute()
            time.sleep(args.tick_sleep)
    except KeyboardInterrupt:
        pass
    finally:
        role.shut()
    return 0


if __name__ == "__main__":
    sys.exit(main())
