#!/usr/bin/env python
"""nf-lint CLI wrapper — `scripts/nf_lint.py --json` exits non-zero on
any unsuppressed finding (CI gate; tier-1 runs the same engine through
tests/test_lint.py).  All flags forward to
`python -m noahgameframe_tpu.lint`; see docs/LINT.md."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from noahgameframe_tpu.lint.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
