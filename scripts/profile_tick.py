"""Per-phase tick profiler: where does the world tick's time go on chip?

Times jit'd PREFIXES of the phase chain (schedule advance -> phase 1 ->
... -> phase i) and reports per-phase deltas, plus the diff-extraction
epilogue (full _trace_step minus the all-phases prefix) and isolated
combat sub-kernels (cell-table build / stencil fold).  Prefix deltas are
the honest attribution under XLA fusion: a phase's cost includes the
bank copies it forces, measured in composition, not in isolation.

Usage:  python scripts/profile_tick.py --entities 1000000 --iters 10
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp


def _timeit(f, arg, iters: int) -> float:
    out = f(arg)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(arg)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1000.0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--entities", type=int, default=1_000_000)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--no-combat", action="store_true")
    ap.add_argument(
        "--platform", choices=("default", "cpu"), default="default",
        help="cpu: force the CPU backend in-process (the sitecustomize "
        "overrides JAX_PLATFORMS env at startup, so the env var alone "
        "cannot)",
    )
    args = ap.parse_args()
    if args.platform == "cpu":
        from noahgameframe_tpu.utils.platform import force_cpu

        force_cpu()
    import os

    from noahgameframe_tpu.utils.platform import init_compile_cache

    os.environ.setdefault("NF_COMPILE_CACHE", "/tmp/nf_xla_cache")
    init_compile_cache()

    from noahgameframe_tpu.game import build_benchmark_world
    from noahgameframe_tpu.kernel.kernel import TickCtx

    world = build_benchmark_world(args.entities, combat=not args.no_combat, seed=42)
    k = world.kernel
    state = k.state
    # every timed prefix is a CostBook entry: phase attribution, compile
    # wall and compiled FLOPs/bytes share one ledger with profile_passes
    # and bench.py instead of re-deriving the phase list
    book = k.costbook

    def prefix_fn(n_phases: int):
        def f(st):
            new_classes = {}
            fired = {}
            for cname in k.store.class_order:
                cs, fm = k.schedule.advance_class(st.classes[cname], st.tick)
                new_classes[cname] = cs
                fired[cname] = fm
            st = st.replace(classes=new_classes)
            rng = jax.random.fold_in(st.rng, st.tick)
            ctx = TickCtx(k, st.tick, rng, fired)
            for ph in k._composed[:n_phases]:
                st = ph.fn(st, ctx)
            return st.replace(tick=st.tick + 1)

        return f

    names = ["schedule"] + [p.name for p in k._composed]
    report = {}
    prev = 0.0
    for i in range(len(k._composed) + 1):
        label = names[i] if i < len(names) else f"phase{i}"
        fn = book.wrap(f"prefix.{label}", prefix_fn(i), stage="profile")
        ms = _timeit(fn, state, args.iters)
        report[label] = round(ms - prev, 3)
        report[f"_cum_{label}"] = round(ms, 3)
        prev = ms
        print(f"  prefix {i:2d} ({label:12s}): {ms:8.2f} ms  (+{report[label]:.2f})", flush=True)

    full = book.wrap("prefix.full_step", lambda st: k._trace_step(st),
                     stage="profile")
    ms_full = _timeit(full, state, args.iters)
    report["diff_epilogue"] = round(ms_full - prev, 3)
    report["full_step"] = round(ms_full, 3)
    print(f"  full step (incl diff):   {ms_full:8.2f} ms  (diff +{report['diff_epilogue']:.2f})", flush=True)

    if world.combat is not None:
        from noahgameframe_tpu.ops.stencil import build_cell_table

        combat = world.combat
        spec = k.store.spec(combat.class_name)
        cs = k.state.classes[combat.class_name]
        pos = cs.vec[:, spec.slot("Position").col, :2]
        n = pos.shape[0]
        bucket = combat.resolved_bucket(n)
        att_bucket = combat.resolved_att_bucket(n)
        vic_feats = jnp.zeros((n, 5), jnp.float32)
        att_feats = jnp.zeros((n, 7), jnp.float32)
        att_mask = cs.alive & (jnp.arange(n) % 30 == 0)  # ~one residue class

        def both_builds(p):
            vt = build_cell_table(
                p, cs.alive, vic_feats, combat.cell_size, combat.width, bucket
            )
            at = build_cell_table(
                p, att_mask, att_feats, combat.cell_size, combat.width, att_bucket
            )
            return vt.payload, at.payload

        build = book.wrap("pass.combat_build_only", both_builds,
                          stage="profile")
        report["combat_build_only"] = round(_timeit(build, pos, args.iters), 3)
        report["combat_geometry"] = {
            "width": combat.width,
            "bucket": bucket,
            "att_bucket": att_bucket,
            "cells": combat.width * combat.width,
        }
        print(
            f"  cell-table builds alone: {report['combat_build_only']:8.2f} ms  "
            f"(width={combat.width}, Kv={bucket}, Ka={att_bucket})",
            flush=True,
        )

    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "entities": args.entities,
                      "profile": report, "costbook": book.snapshot()}))


if __name__ == "__main__":
    main()
