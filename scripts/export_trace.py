#!/usr/bin/env python
"""Capture a Chrome trace-event JSON of a benchmark world run.

    JAX_PLATFORMS=cpu python scripts/export_trace.py --ticks 100 \
        --out /tmp/nf_trace.json

Open the result in Perfetto (https://ui.perfetto.dev) or
chrome://tracing.  The host-side spans come from the SpanTracer the
kernel dispatch/fetch/post stages record into
(telemetry/tracing.py); for the DEVICE timeline use --xprof DIR
instead, which wraps the run in a JAX profiler capture whose HLO ops
carry the per-stage jax.named_scope names (nf.schedule, nf.phase.*,
nf.diff) for XProf/TensorBoard.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--entities", type=int, default=1024)
    ap.add_argument("--ticks", type=int, default=100)
    ap.add_argument("--out", type=Path, default=Path("nf_trace.json"))
    ap.add_argument("--xprof", type=Path, default=None,
                    help="also wrap the run in a JAX profiler capture "
                         "written to this log dir (open with TensorBoard)")
    args = ap.parse_args()

    import contextlib

    from noahgameframe_tpu.game.world import build_benchmark_world
    from noahgameframe_tpu.utils.metrics import profiler_trace

    world = build_benchmark_world(args.entities)
    tracer = world.telemetry.tracer
    tracer.enabled = True
    k = world.kernel

    k.tick()  # compile outside the capture
    tracer.clear()

    prof = (profiler_trace(str(args.xprof)) if args.xprof is not None
            else contextlib.nullcontext())
    with prof:
        for _ in range(args.ticks):
            with tracer.span("tick", tick=k.tick_count):
                k.tick()
    n = tracer.export(args.out)
    print(f"wrote {n} spans over {args.ticks} ticks to {args.out}")
    if args.xprof is not None:
        print(f"device profile in {args.xprof} (tensorboard --logdir)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
