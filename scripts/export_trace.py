#!/usr/bin/env python
"""Capture a Chrome trace-event JSON of a benchmark world run.

    JAX_PLATFORMS=cpu python scripts/export_trace.py --ticks 100 \
        --out /tmp/nf_trace.json

Open the result in Perfetto (https://ui.perfetto.dev) or
chrome://tracing.  The host-side spans come from the SpanTracer the
kernel dispatch/fetch/post stages record into
(telemetry/tracing.py); for the DEVICE timeline use --xprof DIR
instead, which wraps the run in a JAX profiler capture whose HLO ops
carry the per-stage jax.named_scope names (nf.schedule, nf.phase.*,
nf.diff) for XProf/TensorBoard.

Merge mode (ISSUE 7) stitches per-role trace JSONs into ONE Perfetto
timeline with aligned clocks:

    python scripts/export_trace.py --merge game.json proxy.json \
        --offsets-us 0,1234.5 --out cluster.json

Offsets come from the master's /pipeline endpoint
(``clock_offsets_ns``, NTP-style sliding-min estimates) or, for
same-machine tracers, from ``SpanTracer.epoch_ns`` deltas.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def merge_files(paths, offsets_us, out: Path) -> int:
    """Merge chrome-trace JSON docs into one timeline; returns event count."""
    import json

    from noahgameframe_tpu.telemetry.pipeline import merge_chrome_traces

    docs = [json.loads(Path(p).read_text()) for p in paths]
    merged = merge_chrome_traces(docs, offsets_us=offsets_us)
    out.write_text(json.dumps(merged))
    return len(merged["traceEvents"])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--entities", type=int, default=1024)
    ap.add_argument("--ticks", type=int, default=100)
    ap.add_argument("--out", type=Path, default=Path("nf_trace.json"))
    ap.add_argument("--xprof", type=Path, default=None,
                    help="also wrap the run in a JAX profiler capture "
                         "written to this log dir (open with TensorBoard)")
    ap.add_argument("--merge", nargs="+", type=Path, default=None,
                    metavar="TRACE_JSON",
                    help="merge existing chrome-trace files into --out "
                         "instead of running a capture")
    ap.add_argument("--offsets-us", type=str, default=None,
                    help="comma-separated per-file clock offsets (µs) "
                         "added to each merged file's timestamps")
    args = ap.parse_args()

    if args.merge is not None:
        offsets = ([float(x) for x in args.offsets_us.split(",")]
                   if args.offsets_us else None)
        if offsets is not None and len(offsets) != len(args.merge):
            ap.error("--offsets-us must list one offset per --merge file")
        n = merge_files(args.merge, offsets, args.out)
        print(f"merged {len(args.merge)} traces ({n} events) into {args.out}")
        return 0

    import contextlib

    from noahgameframe_tpu.game.world import build_benchmark_world
    from noahgameframe_tpu.utils.metrics import profiler_trace

    world = build_benchmark_world(args.entities)
    tracer = world.telemetry.tracer
    tracer.enabled = True
    k = world.kernel

    k.tick()  # compile outside the capture
    tracer.clear()

    prof = (profiler_trace(str(args.xprof)) if args.xprof is not None
            else contextlib.nullcontext())
    with prof:
        for _ in range(args.ticks):
            with tracer.span("tick", tick=k.tick_count):
                k.tick()
    n = tracer.export(args.out)
    print(f"wrote {n} spans over {args.ticks} ticks to {args.out}")
    if args.xprof is not None:
        print(f"device profile in {args.xprof} (tensorboard --logdir)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
