#!/usr/bin/env python
"""Run the config codegen pipeline (GenerateConfigXML.sh equivalent).

    python scripts/codegen.py --in config_src/ --out NFDataCfg/

Reads CSV/XLSX class sheets (+ `<Class>.ini.csv` element rows) and emits
reference-format Struct/Ini XML, a Python name-constant module, and SQL
DDL.  See noahgameframe_tpu/tools/codegen.py for the sheet layout.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from noahgameframe_tpu.tools import CodegenPipeline  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--in", dest="in_dir", required=True, type=Path)
    ap.add_argument("--out", dest="out_dir", required=True, type=Path)
    args = ap.parse_args()
    report = CodegenPipeline(args.in_dir, args.out_dir).run()
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
