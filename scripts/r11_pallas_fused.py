"""r11 evidence: CostBook-measured combat-stage bytes, split vs fused.

The NF_PALLAS=2 acceptance gate (ISSUE 18): at 20k entities, the
compiled combat stage's `bytes_accessed` (XLA ``cost_analysis`` on CPU
— platform-independent arithmetic, no chip required) must drop >= 30%
under the fused table-free engine vs the split-table path.  This script
measures both arms through the same CostBook ledger bench/profile runs
use and writes ``bench_runs/r11_pallas_fused_cpu.json``.

Two comparisons are recorded, because they answer different questions:

- **output parity** (the headline): the fused kernel returns the AOI
  occupancy counts for free in the same VMEM residency, so the split
  arm needs its second stencil pass (``aoi.neighbor_counts``) to
  produce the same outputs.  split = tables + fold + pull + AOI pass.
- **combat only**: fold outputs alone, no AOI pass on either side.
  Interpret-mode pallas lowers the kernel body's ``[kv, ka, w]``
  pairwise intermediates into the cost model on BOTH arms (~30 MB at
  this geometry, a shared constant), so this delta understates the
  HBM-table savings — it is recorded for honesty, not as the gate.

Both arms run the pallas kernels in interpret mode (the CPU CI path);
geometry comes from the real benchmark world at the requested size, so
the measured stage is exactly the one ``bench.py`` ticks.

Usage::

    JAX_PLATFORMS=cpu python scripts/r11_pallas_fused.py \
        [--entities 20000] [--out bench_runs/r11_pallas_fused_cpu.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--entities", type=int, default=20_000)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument(
        "--out",
        default=os.path.join(os.path.dirname(__file__), "..", "bench_runs",
                             "r11_pallas_fused_cpu.json"),
    )
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from noahgameframe_tpu.game import build_benchmark_world
    from noahgameframe_tpu.ops import aoi
    from noahgameframe_tpu.ops.stencil import (
        build_cell_slots_pair,
        build_cell_table_pair,
        pull,
        pull_slots,
    )
    from noahgameframe_tpu.ops.stencil_pallas import (
        combat_fold_pallas,
        fused_fits_vmem,
        fused_neighborhood,
    )
    from noahgameframe_tpu.telemetry.costbook import CostBook

    n = args.entities
    world = build_benchmark_world(n, combat=True, seed=args.seed)
    k = world.kernel
    k.run_device(1)  # settle: real occupancy, armed timers

    combat = world.combat
    cname = combat.class_name
    spec = k.store.spec(cname)
    cs = k.state.classes[cname]
    pos = cs.vec[:, spec.slot("Position").col, :2]
    alive = cs.alive
    cap = alive.shape[0]
    cell_size, width = combat.cell_size, combat.width
    bucket = combat.resolved_bucket(cap)
    att_bucket = combat.resolved_att_bucket(cap)
    radius = combat.radius
    interval = max(1, k.schedule.ticks_of(combat.attack_period_s))
    attacking = alive & ((jnp.arange(cap) % interval) == 0)

    f32 = jnp.float32
    camp_f = cs.i32[:, spec.slot("Camp").col].astype(f32)
    scene_f = cs.i32[:, spec.slot("SceneID").col].astype(f32)
    group_f = cs.i32[:, spec.slot("GroupID").col].astype(f32)
    atk = cs.i32[:, spec.slot("ATK_VALUE").col]
    eff_atk = jnp.where(attacking, atk, 0).astype(f32)
    rows_f = jnp.arange(cap, dtype=f32)

    # the same feature layouts game/combat.py builds (its docstring is
    # the column contract); partition matches aoi's scene/group packing
    vic_feats = jnp.stack(
        [pos[:, 0], pos[:, 1], camp_f, scene_f, group_f], -1
    )
    att_feats = jnp.stack(
        [pos[:, 0], pos[:, 1], eff_atk, camp_f, scene_f, group_f, rows_f], -1
    )
    bank = jnp.stack(
        [pos[:, 0], pos[:, 1], camp_f, scene_f, group_f, eff_atk], -1
    )
    partition = (cs.i32[:, spec.slot("SceneID").col] << 12) | \
        cs.i32[:, spec.slot("GroupID").col]

    def split_combat(p, al, am, vf, af):
        vt, at = build_cell_table_pair(
            p, al, vf, am, af, cell_size, width, bucket, att_bucket
        )
        inc, bestr = combat_fold_pallas(vt, at, radius, interpret=True)
        res = pull(vt, jnp.stack([inc, bestr], -1).astype(f32),
                   fill=(0.0, -1.0))
        return res, vt.dropped, at.dropped

    def split_aoi(p, al, part):
        # the second stencil pass the split path needs for output
        # parity: the fused kernel folds this count in-residency
        return aoi.neighbor_counts(
            p, al, radius, cell_size, width, bucket, part
        )

    def fused(bk, p, al, am):
        vs, ats = build_cell_slots_pair(
            p, al, am, cell_size, width, bucket, att_bucket
        )
        inc, bestr, nbr = fused_neighborhood(
            bk, vs, ats, radius, interpret=True
        )
        res = pull_slots(
            vs.slot_of,
            jnp.stack([inc, bestr, nbr], -1).astype(f32),
            fill=(0.0, -1.0, 0.0),
        )
        return res, vs.dropped, ats.dropped

    book = CostBook()
    runs = (
        ("r11.split_combat", split_combat,
         (pos, alive, attacking, vic_feats, att_feats)),
        ("r11.split_aoi", split_aoi, (pos, alive, partition)),
        ("r11.fused", fused, (bank, pos, alive, attacking)),
    )
    cost = {}
    for name, fn, fargs in runs:
        wrapped = book.wrap(name, fn, stage="profile")
        jax.block_until_ready(wrapped(*fargs))
        e = book.entries[name].last
        cost[name] = {
            "bytes_accessed": int(e.get("bytes_accessed", 0)),
            "flops": int(e.get("flops", 0)),
            "temp_bytes": int(e.get("temp_bytes", 0)),
        }

    sc = cost["r11.split_combat"]["bytes_accessed"]
    sa = cost["r11.split_aoi"]["bytes_accessed"]
    fu = cost["r11.fused"]["bytes_accessed"]
    parity_drop = 1.0 - fu / max(1, sc + sa)
    combat_drop = 1.0 - fu / max(1, sc)
    fits, need, budget = fused_fits_vmem(cap, width, bucket, att_bucket)

    out = {
        "metric": "combat_stage_bytes_drop_fused_vs_split",
        "value": round(parity_drop, 4),
        "unit": "fraction",
        "pass": bool(parity_drop >= 0.30),
        "detail": {
            "entities": n,
            "seed": args.seed,
            "geometry": {
                "width": width, "cell_size": cell_size,
                "bucket": bucket, "att_bucket": att_bucket,
                "radius": radius, "capacity": cap,
            },
            "methodology": (
                "XLA cost_analysis via the CostBook ledger on CPU; both "
                "arms run their pallas kernels in interpret mode (the "
                "CI parity path).  Headline delta compares equal "
                "OUTPUTS: the fused kernel also returns the AOI "
                "occupancy counts, so the split arm includes the "
                "aoi.neighbor_counts pass it needs to match.  The "
                "combat-only delta is understated: interpret mode "
                "lowers the kernel body's [kv,ka,w] pairwise "
                "intermediates into the cost model on both arms."
            ),
            "bytes_accessed": {
                "split_combat_only": sc,
                "split_aoi_pass": sa,
                "split_with_aoi": sc + sa,
                "fused": fu,
            },
            "drop_output_parity": round(parity_drop, 4),
            "drop_combat_only": round(combat_drop, 4),
            "cost_entries": cost,
            "vmem": {"fits": bool(fits), "need_bytes": int(need),
                     "budget_bytes": int(budget)},
            "platform": jax.devices()[0].platform,
        },
    }
    path = os.path.abspath(args.out)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
