#!/usr/bin/env python
"""Telemetry smoke test: boot a Game role, tick it, scrape /metrics.

    JAX_PLATFORMS=cpu python scripts/telemetry_smoke.py

Boots a GameRole on loopback, drives 50 world ticks through the real
pump, scrapes /metrics over a real socket, and asserts the tick
histogram and the on-device overflow counters are present.  Exits 0 on
success — wire it into CI next to bench smoke runs.
"""

from __future__ import annotations

import socket
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

TICKS = 50


def scrape(pump, port: int, path: bytes = b"/metrics") -> bytes:
    """GET over a blocking client socket against the pumped HttpServer."""
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.settimeout(0.02)
    s.sendall(b"GET " + path + b" HTTP/1.1\r\nHost: smoke\r\n"
              b"Connection: close\r\n\r\n")
    buf = b""
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        pump()
        try:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
        except socket.timeout:
            head, sep, body = buf.partition(b"\r\n\r\n")
            if sep:
                cl = [ln for ln in head.split(b"\r\n")
                      if ln.lower().startswith(b"content-length")]
                if cl and len(body) >= int(cl[0].split(b":")[1]):
                    break
    s.close()
    return buf


def main() -> int:
    from noahgameframe_tpu.game.world import build_benchmark_world
    from noahgameframe_tpu.net.roles.base import RoleConfig
    from noahgameframe_tpu.net.roles.game import GameRole

    # combat ON: the AOI/stencil overflow counters only exist in worlds
    # with a combat phase (they come from its cell-table builds)
    world = build_benchmark_world(256)
    role = GameRole(RoleConfig(6, 0, "SmokeGame", "127.0.0.1", 0),
                    world=world)
    http = role.serve_metrics(0)
    print(f"game role up; /metrics on 127.0.0.1:{http.port}")

    dt = role.game_world.config.dt * 1.0001
    now = 1000.0
    ticked = role.kernel.tick_count
    while role.kernel.tick_count - ticked < TICKS:
        now += dt
        role.execute(now)

    raw = scrape(role.execute, http.port)
    status = raw.split(b"\r\n", 1)[0]
    body = raw.partition(b"\r\n\r\n")[2].decode()
    role.shut()

    checks = {
        "http 200": b"200" in status,
        "tick histogram": "nf_game_tick_seconds_bucket{le=" in body,
        "frame histogram": "nf_frame_seconds_bucket{le=" in body,
        "victim overflow counter":
            'nf_tick_counters_total{counter="aoi_victim_overflow_drops"}'
            in body,
        "attacker overflow counter":
            'nf_tick_counters_total{counter="aoi_attacker_overflow_drops"}'
            in body,
        # scrape pumps tick the world too — assert the floor, not equality
        "tick count": any(
            ln.startswith("nf_ticks_total ")
            and float(ln.split()[1]) >= TICKS
            for ln in body.splitlines()
        ),
    }
    failed = [name for name, ok in checks.items() if not ok]
    for name, ok in checks.items():
        print(f"  {'ok  ' if ok else 'FAIL'} {name}")
    if failed:
        print(f"SMOKE FAILED: {failed}")
        return 1
    print(f"SMOKE OK: {TICKS} ticks, {len(body.splitlines())} metric lines")
    return 0


if __name__ == "__main__":
    sys.exit(main())
