#!/usr/bin/env python
"""Write-behind persistence smoke: kill a game server mid-flush while the
store is down, revive it from the durable (checkpoint, WAL) pair, and
prove both the world AND the store converged to the fault-free answer.

    JAX_PLATFORMS=cpu python scripts/persist_smoke.py

Boots the five-role LocalCluster from chaos_smoke's world recipe plus a
few persisted players, with a write-behind pipeline (persist/
writebehind.py) flushing Save-flagged per-tick diffs into a shared
MemoryKV through a seeded store FaultPlan (refuse-first-N, latency
spikes, a hard down window by op count).  The scenario:

- early flushes retry through the refuse-first budget and land,
- scripted Gold writes + regen dynamics keep the dirty stream flowing,
- a checkpoint (with its WAL fsync barrier) pins the durable pair,
- the store goes DOWN: the queue fills, lag grows, the master's /json
  shows the game degraded — and the tick loop KEEPS TICKING (asserted
  via the tick-latency histogram and the flusher-thread ledger),
- the game role is hard-killed mid-outage; queued batches survive only
  in the staging WAL,
- the revived role recovers the WAL suffix, rides out the rest of the
  outage, then drains to lag 0,
- the final world is bit-identical to a fault-free control (full bank
  compare + the journal's per-tick state digests), and every store blob
  equals the revived world's own Save-pack snapshot.

Exits 0 on success — wire it into CI next to the chaos/replay smokes.
"""

from __future__ import annotations

import sys
import tempfile
import threading
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from chaos_smoke import build_world  # noqa: E402
from telemetry_smoke import scrape  # noqa: E402

PLAYERS = 3
EXTRA_TICKS = 20
LATENCY_S = 0.1
PERSIST_SERIES = (
    "nf_persist_flush_total", "nf_persist_retry_total",
    "nf_persist_lag_ticks", "nf_persist_queue_depth",
    "nf_persist_degraded",
)


def seed_players(world) -> list:
    """Deterministic persisted players on top of the chaos world: fixed
    guids (the default allocator is wall-clock based), regen armed so
    the Save-flagged dirty stream flows without any host input."""
    from noahgameframe_tpu.core.datatypes import Guid
    from noahgameframe_tpu.game.defines import (
        COMM_PROPERTY_RECORD,
        PropertyGroup,
    )

    # install the role's default stat table up front so the live world
    # (which gets it from GameRole's empty-config fallback) and the
    # bare control world run the identical compiled level phase
    pc = world.property_config
    if not np.any(pc._base):
        pc.fill_linear(
            0,
            base={"MAXHP": 100, "MAXMP": 50, "MAXSP": 50, "HPREGEN": 1,
                  "ATK_VALUE": 10, "DEF_VALUE": 5, "MOVE_SPEED": 30000},
            per_level={"MAXHP": 20, "ATK_VALUE": 2, "DEF_VALUE": 1},
        )
        pc.freeze()
    k = world.kernel
    guids = []
    for i in range(PLAYERS):
        guids.append(k.create_object(
            "Player",
            {"Name": f"Hero{i}", "Account": f"acct{i}",
             "Gold": 100 + i, "HP": 40 + 5 * i},
            guid=Guid(9, 1000 + i), scene=1, group=1,
        ))
    k.state = k.store.record_write_rows(
        k.state, "Player", np.arange(PLAYERS), COMM_PROPERTY_RECORD,
        int(PropertyGroup.EFFECTVALUE),
        {"MAXHP": [200] * PLAYERS, "HPREGEN": [1] * PLAYERS},
    )
    world.regen.arm_all("Player")
    return guids


def store_plan(seed: int):
    """Transport faults from the chaos smoke stay off here — this smoke
    isolates the store leg: a refuse-first budget at boot, probabilistic
    latency spikes throughout, and a hard outage over ops [40, 120).
    The op clock lives in the ChaosDirector, so the revived role's
    rebuilt pipeline CONTINUES the outage instead of restarting it."""
    from noahgameframe_tpu.net.chaos import FaultPlan, StoreFaults

    return FaultPlan(seed=seed, stores={
        "game6.store": StoreFaults(
            fail_first=2,
            latency=0.2, latency_s=LATENCY_S,
            down=((40, 120),),
        ),
    })


def _ext(cluster, role: str, sid: int) -> dict:
    for e in cluster.master.servers_status()["servers"].get(role, []):
        if e["server_id"] == sid:
            return e.get("ext", {})
    return {}


def _drive_control(world, until_tick: int, writes) -> dict:
    """Replay GameRole.execute's exact per-tick module ordering,
    applying the recorded host writes at their recorded tick counts;
    returns tick -> uint32 state digest (the journal's tick_mark form)."""
    pm, k = world.pm, world.kernel
    digests = {}
    by_tick = {}
    for tick, fn in writes:
        by_tick.setdefault(tick, []).append(fn)
    for fn in by_tick.pop(k.tick_count, []):
        fn(world)
    while k.tick_count < until_tick:
        for m in pm.modules.values():
            if m is not k:
                m.execute()
        k.execute()
        k.tick()
        pm.frame += 1
        digests[k.tick_count] = (
            int(k.last_counters.get("state_digest", 0)) & 0xFFFFFFFF
        )
        for fn in by_tick.pop(k.tick_count, []):
            fn(world)
    return digests


def run(tmpdir, seed: int = 7) -> dict:
    """Run the whole scenario; returns {check name: bool}."""
    from noahgameframe_tpu.net.retry import RetryPolicy
    from noahgameframe_tpu.net.roles.cluster import LocalCluster
    from noahgameframe_tpu.persist.agent import PlayerDataAgent
    from noahgameframe_tpu.persist.checkpoint import _flatten_state
    from noahgameframe_tpu.persist.codec import snapshot_object
    from noahgameframe_tpu.persist.kv import MemoryKV
    from noahgameframe_tpu.replay.journal import read_ticks

    ckpt = Path(tmpdir) / "ckpt"
    wal = Path(tmpdir) / "wal"
    jdir = Path(tmpdir) / "journal"
    kv = MemoryKV()
    world = build_world(seed)
    guids = seed_players(world)
    cluster = LocalCluster(
        http_port=0,
        game_world=world,
        game_kwargs={
            "checkpoint_dir": ckpt,
            "checkpoint_seconds": 3600.0,  # checkpoints are explicit below
            "journal_dir": jdir,
            "data_agent": PlayerDataAgent(kv),
            "persist_store": kv,
            "persist_wal_dir": wal,
            "persist_drain_timeout": 0.3,
            "autosave_seconds": 3600.0,  # the diff spine is the saver now
        },
    )
    checks = {}
    revived = None
    writes = []  # (tick_count at write, fn) — replayed into the control
    main_thread = threading.get_ident()
    try:
        cluster.apply_chaos(store_plan(seed))
        game = cluster.game
        cluster.start(timeout=60)
        checks["wired under store faults"] = True
        pipeline = game.persist
        checks["pipeline wired"] = pipeline is not None

        # ---- phase A: refuse-first budget retries, then flushes land
        checks["first flush lands after retries"] = cluster.pump_until(
            lambda: pipeline.flushes_total >= 1, timeout=60
        )
        checks["refuse-first retries counted"] = pipeline.retries_total >= 2

        # ---- phase B: scripted Gold writes, recorded for the control.
        # All host writes land BEFORE the checkpoint: the revived run
        # re-executes only post-checkpoint ticks, which must need no
        # host input to match the control.
        for i, g in enumerate(guids):
            target = game.kernel.tick_count + 3
            cluster.pump_until(
                lambda t=target: game.kernel.tick_count >= t, timeout=30)
            tick = game.kernel.tick_count

            def w(wld, gg=g, v=1000 * (i + 1)):
                wk = wld.kernel
                wk.state = wk.store.set_property(wk.state, gg, "Gold", v)

            w(game.game_world)
            writes.append((tick, w))
        checks["gold writes staged"] = True

        # ---- durable pair: checkpoint + WAL fsync barrier
        game.checkpoint_now()
        checks["checkpoint + barrier written"] = (ckpt / "meta.json").exists()

        # ---- phase C: the down window opens; degraded, never blocked
        checks["store outage observed"] = cluster.pump_until(
            lambda: pipeline.degraded() and pipeline.queue_depth() >= 2,
            timeout=60,
        )
        t_deg = game.kernel.tick_count
        cluster.pump_until(
            lambda: game.kernel.tick_count >= t_deg + 10, timeout=30)
        checks["ticks advance while degraded"] = (
            game.kernel.tick_count >= t_deg + 10 and pipeline.degraded()
        )
        checks["lag gauge grows"] = pipeline.lag_ticks() > 0
        checks["degraded on master /json ext"] = cluster.pump_until(
            lambda: _ext(cluster, "game", 6).get("persist_degraded") == "1",
            timeout=30,
        )
        # tick-time telemetry: neither the injected store latency (0.1 s
        # sleeps) nor the outage ever reaches the tick path
        hist = game.telemetry.registry.get("nf_game_tick_seconds")
        checks["tick p50 below injected store latency"] = (
            0.0 < hist.percentile(50) < LATENCY_S
        )
        checks["store calls never on the pump thread"] = (
            len(pipeline.store_threads) > 0
            and main_thread not in pipeline.store_threads
        )
        wal_batches = pipeline.wal.batches_total

        # ---- kill mid-outage: queued batches survive only in the WAL
        cluster.kill_role("Game1")
        checks["WAL retained pending batches"] = wal_batches > 0 and any(
            wal.glob("wal-*.nfw"))

        # ---- revive from the durable (checkpoint, WAL) pair
        revived = cluster.revive_role("Game1", world=build_world(seed),
                                      resume=True)
        rp = revived.persist
        checks["WAL suffix recovered"] = rp.recovered_batches > 0
        # ride out the rest of the down window fast (each retry burns
        # one op against the plan's deterministic [40, 120) schedule)
        rp.retry = RetryPolicy(base=0.003, cap=0.01, seed=seed)
        checks["revived game rewired"] = cluster.pump_until(
            lambda: cluster.wired(), timeout=60
        )
        checks["store heals and queue drains"] = cluster.pump_until(
            lambda: rp.queue_depth() == 0 and rp.lag_ticks() == 0
            and not rp.degraded(),
            timeout=120,
        )
        target = revived.kernel.tick_count + EXTRA_TICKS
        cluster.pump_until(
            lambda: revived.kernel.tick_count >= target, timeout=30)
        checks["healthy on master /json ext"] = cluster.pump_until(
            lambda: _ext(cluster, "game", 6).get("persist_degraded") == "0",
            timeout=30,
        )

        # ---- freeze: stop pumping (no more ticks) and flush the tail
        # so the store reflects the final world before the comparisons
        checks["final drain"] = rp.drain(timeout=10.0)

        # ---- world bit-identical to the fault-free control
        control = build_world(seed)
        seed_players(control)
        control.kernel.enable_digest()
        digests = _drive_control(control, revived.kernel.tick_count, writes)
        a = _flatten_state(revived.kernel.state)
        b = _flatten_state(control.kernel.state)
        keys = [key for key in b
                if key.startswith("c/NPC/") or key.startswith("c/Player/")]
        checks["world matches fault-free control"] = (
            int(a["tick"]) == int(b["tick"])
            and np.array_equal(a["rng"], b["rng"])
            and all(np.array_equal(a[key], b[key]) for key in keys)
        )

        # ---- journal digest stream (both runs' records, the revived
        # run overwriting the overlap) matches the control everywhere.
        # The revived writer is still OPEN: sync it first, or the strict
        # reader sees its buffered tail as a torn segment.
        if revived.journal is not None:
            revived.journal.sync()
        recorded = read_ticks(jdir)
        overlap = [t for t in recorded if t in digests]
        checks["journal digest stream matches control"] = (
            len(overlap) > 30
            and all(recorded[t] == digests[t] for t in overlap)
        )

        # ---- every store blob equals the live Save-pack snapshot
        rk = revived.kernel
        agent = revived.data_agent
        checks["store blobs match world snapshots"] = all(
            kv.get(agent._key_of(g)) == snapshot_object(
                rk.store, rk.state, g, agent.flags)
            for g in guids
        )
        checks["idempotence watermark written"] = (
            kv.get("__wb__:game6") is not None
        )

        # ---- telemetry: counters moved, /metrics serves all five series
        reg = revived.telemetry.registry
        checks["flush counter moved"] = reg.value("nf_persist_flush_total") > 0
        checks["retry counter moved"] = reg.value("nf_persist_retry_total") > 0
        checks["latency spikes injected"] = (
            cluster.chaos.total("store_latency") > 0
        )
        checks["outage ops refused"] = cluster.chaos.total("store_down") > 0
        game_http = revived.serve_metrics(0)
        body = scrape(
            cluster.execute, game_http.port
        ).partition(b"\r\n\r\n")[2].decode()
        for series in PERSIST_SERIES:
            checks[f"/metrics serves {series}"] = any(
                ln.startswith(series) for ln in body.splitlines()
            )
    finally:
        cluster.shut()
        if revived is not None and revived not in cluster.roles:
            revived.shut()
    return checks


def main() -> int:
    with tempfile.TemporaryDirectory() as tmpdir:
        checks = run(tmpdir)
    failed = [name for name, ok in checks.items() if not ok]
    for name, ok in checks.items():
        print(f"  {'ok  ' if ok else 'FAIL'} {name}")
    if failed:
        print(f"PERSIST SMOKE FAILED: {failed}")
        return 1
    print(f"PERSIST SMOKE OK: {len(checks)} checks")
    return 0


if __name__ == "__main__":
    sys.exit(main())
