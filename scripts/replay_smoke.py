#!/usr/bin/env python
"""Flight-recorder smoke: journal a chaos run, replay it bit-identically,
then bisect a deliberately perturbed replay to the exact injected tick.

    JAX_PLATFORMS=cpu python scripts/replay_smoke.py

Boots the five-role LocalCluster from chaos_smoke's world recipe with a
seeded FaultPlan AND a journaling game role, copies the first checkpoint
aside, runs 120+ journaled ticks under faults, and asserts:

- the master's /json aggregate exposes the chaos seed + link budgets
  (the replay side can re-derive the fault schedule),
- the journal telemetry moved (ticks/bytes/segments counters),
- an offline replay from (checkpoint, journal) reproduces EVERY
  per-tick on-device state digest bit for bit — the chaos run is
  deterministic modulo its recorded inputs,
- a second replay with one float perturbed at a chosen tick diverges,
  and digest bisection pins the FIRST divergent tick exactly there,
  with a field-level diff naming the perturbed bank.

Exits 0 on success — wire it into CI next to the chaos smoke.
"""

from __future__ import annotations

import shutil
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from chaos_smoke import build_world, fault_plan  # noqa: E402

TICKS = 120  # journaled ticks past the base checkpoint
PERTURB_AFTER = 40  # perturbation lands this many ticks past the base


def run(tmpdir, seed: int = 7) -> dict:
    """Run the whole scenario; returns {check name: bool}."""
    import json

    from noahgameframe_tpu.net.roles.cluster import LocalCluster
    from noahgameframe_tpu.replay import (
        bisect_divergence,
        field_diff,
        make_offline_role,
        read_ticks,
        replay_journal,
    )

    ckpt = Path(tmpdir) / "ckpt"
    ckpt0 = Path(tmpdir) / "ckpt0"
    jdir = Path(tmpdir) / "journal"
    cluster = LocalCluster(
        http_port=0,
        game_world=build_world(seed),
        game_kwargs={
            "checkpoint_dir": ckpt,
            "checkpoint_seconds": 0.2,
            "journal_dir": jdir,
            "journal_segment_bytes": 4096,
        },
    )
    checks = {}
    try:
        cluster.apply_chaos(fault_plan(seed))
        cluster.start(timeout=60)
        checks["wired under faults"] = True
        checks["checkpoint written"] = cluster.pump_until(
            lambda: (ckpt / "meta.json").exists(), timeout=30
        )
        # freeze the base checkpoint before the periodic writer replaces
        # it (single pump thread: nothing is mid-rename between pumps)
        shutil.copytree(ckpt, ckpt0)
        base_tick = json.loads((ckpt0 / "meta.json").read_text())["tick_count"]

        game = cluster.game
        checks["journaled 120+ ticks under chaos"] = cluster.pump_until(
            lambda: game.kernel.tick_count >= base_tick + TICKS, timeout=120
        )

        # ---- the chaos plan is visible where replay needs it
        status = cluster.master.servers_status()
        chaos = status.get("chaos", {})
        checks["chaos seed on master /json"] = chaos.get("seed") == seed
        checks["chaos link budgets on master /json"] = (
            "game6.world" in chaos.get("links", {})
        )

        # ---- journal telemetry moved
        reg = game.telemetry.registry
        checks["journal tick counter"] = (
            reg.value("nf_journal_ticks_total") >= TICKS
        )
        checks["journal byte counter"] = reg.value("nf_journal_bytes_total") > 0
        checks["journal segment rotation"] = (
            reg.value("nf_journal_segments_total") >= 2
        )
    finally:
        cluster.shut()

    # ------------------------------------------------- faithful replay
    expected = read_ticks(jdir)
    checks["journal readable after shutdown"] = len(expected) >= TICKS
    checks["chaos note journaled"] = any(
        n.get("kind") == "chaos" and n.get("seed") == seed
        for n in _journal_notes(jdir)
    )

    role = make_offline_role(world=build_world(seed))
    try:
        rep = replay_journal(jdir, checkpoint=ckpt0, role=role)
        checks["replayed 100+ ticks"] = rep.ticks_replayed >= 100
        checks["replay digests bit-identical"] = rep.ok
        checks["replay divergence counter zero"] = (
            role.telemetry.registry.value("nf_replay_divergences_total") == 0
        )
        clean_state = role.kernel.state
    finally:
        role.shut()

    # --------------------------------------- perturbed replay + bisect
    # nudge one NPC position component: movement is off, so nothing ever
    # rewrites the vec bank and the divergence persists tick after tick
    # (HP would heal back to the MAXHP cap and break bisect's monotone
    # boundary) — exactly the class of bug bisect exists to localize
    k_t = base_tick + PERTURB_AFTER

    def perturb(prole, tick):
        if tick != k_t:
            return
        from noahgameframe_tpu.core.store import with_class

        k = prole.kernel
        cs = k.state.classes["NPC"]
        k.state = with_class(k.state, "NPC",
                             cs.replace(vec=cs.vec.at[0, 0, 0].add(1.0)))

    role2 = make_offline_role(world=build_world(seed))
    try:
        rep2 = replay_journal(jdir, checkpoint=ckpt0, role=role2,
                              perturb=perturb)
        checks["perturbed replay diverges"] = not rep2.ok
        checks["divergence counter moved"] = (
            role2.telemetry.registry.value("nf_replay_divergences_total") >= 1
        )
        found = bisect_divergence(rep2.expected, rep2.digests)
        checks["bisect finds exact perturbed tick"] = found == k_t
        diff = field_diff(role2.kernel.state, clean_state)
        checks["field diff names perturbed bank"] = any(
            d["key"] == "c/NPC/vec" for d in diff
        )
    finally:
        role2.shut()
    return checks


def _journal_notes(jdir) -> list:
    from noahgameframe_tpu.replay.journal import (
        REC_NOTE,
        JournalReader,
        decode_json,
    )

    return [decode_json(body) for rec_type, body in JournalReader(jdir)
            if rec_type == REC_NOTE]


def main() -> int:
    with tempfile.TemporaryDirectory() as tmpdir:
        checks = run(tmpdir)
    failed = [name for name, ok in checks.items() if not ok]
    for name, ok in checks.items():
        print(f"  {'ok  ' if ok else 'FAIL'} {name}")
    if failed:
        print(f"REPLAY SMOKE FAILED: {failed}")
        return 1
    print(f"REPLAY SMOKE OK: {len(checks)} checks")
    return 0


if __name__ == "__main__":
    sys.exit(main())
