#!/usr/bin/env python
"""Chaos smoke: kill a game server under fault injection, revive it from
its atomic checkpoint, and prove the cluster converged to the fault-free
answer.

    JAX_PLATFORMS=cpu python scripts/chaos_smoke.py

Boots the five-role LocalCluster with a seeded FaultPlan (drops, dups,
delays, corruption, connection refusal, a timed login<->master
partition), waits for a checkpoint, hard-kills the game role, watches
the master's heartbeat lease flip it DOWN, revives it with ``--resume``
semantics, and then asserts:

- the master shows the game DOWN then UP again (lease state),
- the revived world's NPC banks + tick + rng exactly match a fault-free
  control world driven the same number of ticks (faults may delay the
  cluster, never corrupt the simulation),
- the injected-fault / retry / lease-expiry / recovery counters are all
  nonzero and visible over real /metrics scrapes.

Exits 0 on success — wire it into CI next to the telemetry smoke.
"""

from __future__ import annotations

import sys
import tempfile
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from telemetry_smoke import scrape  # noqa: E402

NPCS = 8
EXTRA_TICKS = 20


def build_world(seed: int = 7):
    """One deterministic world recipe used three times: the live world,
    the revive substrate (overwritten by the checkpoint load), and the
    fault-free control.  Regen is the only dynamic phase, so the world
    evolves tick-by-tick with zero host input."""
    from noahgameframe_tpu.game.defines import (
        COMM_PROPERTY_RECORD,
        PropertyGroup,
    )
    from noahgameframe_tpu.game.world import GameWorld, WorldConfig

    w = GameWorld(WorldConfig(
        npc_capacity=64, player_capacity=8, seed=seed,
        combat=False, movement=False, regen=True, middleware=False,
        regen_period_s=0.1,
    )).start()
    # mirror GameRole's scene bring-up so the control world (never
    # attached to a role) starts from the identical host state
    if 1 not in w.scene.scenes:
        w.scene.create_scene(1)
    if 1 not in w.scene.scenes[1].groups:
        w.scene.request_group(1)
    w.seed_npcs(NPCS, hp=100)
    # raise MAXHP above HP so the regen phase has real dynamics to replay
    k = w.kernel
    k.state = k.store.record_write_rows(
        k.state, "NPC", np.arange(NPCS), COMM_PROPERTY_RECORD,
        int(PropertyGroup.EFFECTVALUE), {"MAXHP": [200] * NPCS},
    )
    return w


def fault_plan(seed: int):
    from noahgameframe_tpu.net.chaos import FaultPlan, LinkFaults

    return FaultPlan(seed=seed, links={
        # refuse exercises the RetryPolicy backoff on the game's world link
        "game6.world": LinkFaults(refuse=0.25, drop=0.05, dup=0.05),
        # refuse_first=2 guarantees retries on a link whose role survives
        # the whole scenario (the game role is killed, taking its
        # retries_total with it)
        "proxy5.world": LinkFaults(refuse_first=2, drop=0.05, dup=0.1,
                                   delay=0.1, delay_polls=5),
        # corrupt/truncate exercise the dispatch fault isolation
        "proxy5.games": LinkFaults(dup=0.1, corrupt=0.05, truncate=0.05),
        # timed both-way partition; heals when the window closes
        "login4.master": LinkFaults(partitions=((200, 400, "both"),)),
    })


def _lease(cluster, role: str, sid: int):
    for e in cluster.master.servers_status()["servers"].get(role, []):
        if e["server_id"] == sid:
            return e["lease"]
    return None


def _drive_control(world, ticks: int) -> None:
    """Replay GameRole.execute's exact per-tick module ordering."""
    pm, k = world.pm, world.kernel
    while k.tick_count < ticks:
        for m in pm.modules.values():
            if m is not k:
                m.execute()
        k.execute()
        k.tick()
        pm.frame += 1


def run(tmpdir, seed: int = 7) -> dict:
    """Run the whole scenario; returns {check name: bool}."""
    from noahgameframe_tpu.net.roles.cluster import LocalCluster
    from noahgameframe_tpu.persist.checkpoint import _flatten_state

    ckpt = Path(tmpdir) / "ckpt"
    cluster = LocalCluster(
        http_port=0,
        game_world=build_world(seed),
        lease_suspect_seconds=0.6,
        lease_down_seconds=1.2,
        game_kwargs={"checkpoint_dir": ckpt, "checkpoint_seconds": 0.3},
    )
    checks = {}
    revived = None
    try:
        cluster.apply_chaos(fault_plan(seed))
        cluster.start(timeout=60)
        checks["wired under faults"] = True
        checks["checkpoint written"] = cluster.pump_until(
            lambda: (ckpt / "meta.json").exists(), timeout=30
        )
        cluster.kill_role("Game1")
        checks["master marks game DOWN"] = cluster.pump_until(
            lambda: _lease(cluster, "game", 6) == "DOWN", timeout=30
        )
        revived = cluster.revive_role("Game1", world=build_world(seed),
                                      resume=True)
        reg = revived.telemetry.registry
        checks["resume restored checkpoint"] = (
            reg.value("nf_recoveries_total") == 1
        )
        checks["master marks game UP"] = cluster.pump_until(
            lambda: _lease(cluster, "game", 6) == "UP" and cluster.wired(),
            timeout=60,
        )
        target = revived.kernel.tick_count + EXTRA_TICKS
        checks["revived world ticking"] = cluster.pump_until(
            lambda: revived.kernel.tick_count >= target, timeout=30
        )

        # ---- determinism: revived == fault-free control at equal tick
        control = build_world(seed)
        _drive_control(control, revived.kernel.tick_count)
        a = _flatten_state(revived.kernel.state)
        b = _flatten_state(control.kernel.state)
        npc_keys = [key for key in b if key.startswith("c/NPC/")]
        checks["world matches fault-free control"] = (
            int(a["tick"]) == int(b["tick"])
            and np.array_equal(a["rng"], b["rng"])
            and all(np.array_equal(a[key], b[key]) for key in npc_keys)
        )

        # ---- counters (in-process reads)
        checks["faults injected"] = cluster.chaos.total() > 0
        retries = sum(
            sum(p.retries_total.values())
            for r in cluster.roles for p in r.clients.values()
        )
        checks["retries counted"] = retries > 0
        checks["lease expiry counted"] = (
            cluster.master.telemetry.registry.value(
                "nf_lease_expirations_total", role="game") >= 1
        )
        checks["partition healed"] = (
            cluster.chaos.total("partition_out") > 0
            and _lease(cluster, "login", 4) == "UP"
        )

        # ---- the same story over real /metrics scrapes
        master_body = scrape(
            cluster.execute, cluster.master.http.port
        ).partition(b"\r\n\r\n")[2].decode()
        checks["/metrics lease counters"] = any(
            ln.startswith('nf_lease_expirations_total{role="game"}')
            and float(ln.split()[-1]) >= 1
            for ln in master_body.splitlines()
        )
        game_http = revived.serve_metrics(0)
        game_body = scrape(
            cluster.execute, game_http.port
        ).partition(b"\r\n\r\n")[2].decode()
        checks["/metrics chaos counters"] = any(
            ln.startswith("nf_chaos_faults_total{")
            and float(ln.split()[-1]) > 0
            for ln in game_body.splitlines()
        )
        checks["/metrics recovery counter"] = any(
            ln.startswith("nf_recoveries_total ")
            and float(ln.split()[-1]) == 1
            for ln in game_body.splitlines()
        )
        proxy_http = cluster.proxy.serve_metrics(0)
        proxy_body = scrape(
            cluster.execute, proxy_http.port
        ).partition(b"\r\n\r\n")[2].decode()
        checks["/metrics retry counters"] = any(
            ln.startswith("nf_reconnects_total{")
            and float(ln.split()[-1]) > 0
            for ln in proxy_body.splitlines()
        )
    finally:
        cluster.shut()
        if revived is not None and revived not in cluster.roles:
            revived.shut()
    return checks


def main() -> int:
    with tempfile.TemporaryDirectory() as tmpdir:
        checks = run(tmpdir)
    failed = [name for name, ok in checks.items() if not ok]
    for name, ok in checks.items():
        print(f"  {'ok  ' if ok else 'FAIL'} {name}")
    if failed:
        print(f"CHAOS SMOKE FAILED: {failed}")
        return 1
    print(f"CHAOS SMOKE OK: {len(checks)} checks")
    return 0


if __name__ == "__main__":
    sys.exit(main())
