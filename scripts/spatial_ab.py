"""A/B: spatial slab sharding vs XLA-partitioned global sort.

Runs the SAME combat computation (walk + cell tables + 3x3 fold +
damage) at benchmark scale over an N-device mesh two ways:

  global  — entity-axis sharding, one jit over the whole array; XLA
            partitions the argsort into a distributed sort (the
            parallel/shard.py strategy).
  spatial — parallel/spatial.py: per-shard local sort, dense ppermute
            halos, budgeted migration.

On the virtual CPU mesh the absolute ms are NOT chip predictions, but
compile time and the collective structure are real, and the two paths'
results are cross-checked (identical HP totals within budgets).  Emits
one JSON line for bench_runs/.

Usage: python scripts/spatial_ab.py [--entities 524288] [--shards 8]
                                    [--ticks 4]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--entities", type=int, default=524_288)
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--ticks", type=int, default=4)
    ap.add_argument("--skip-global", action="store_true",
                    help="spatial side only (the global sort at 4M on a "
                         "virtual mesh costs minutes/tick; the 512k "
                         "artifact already ranks the two)")
    ap.add_argument("--skin", type=float, default=0.0,
                    help="Verlet skin (ops/verlet.py): > 0 inflates the "
                         "cell to radius + skin and gates the per-shard "
                         "argsort on displacement; the global reference "
                         "runs the SAME inflated geometry so parity stays "
                         "bit-exact")
    args = ap.parse_args()

    from noahgameframe_tpu.utils.platform import force_cpu, init_compile_cache

    force_cpu(args.shards)
    init_compile_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from noahgameframe_tpu.ops.stencil import auto_bucket
    from noahgameframe_tpu.parallel.mesh import make_mesh
    from noahgameframe_tpu.parallel.spatial import (
        SpatialGeom,
        SpatialWorld,
        reference_step,
    )

    n = args.entities
    # benchmark density (~0.4/unit^2), cell 4.0 — same recipe as
    # game.world.build_benchmark_world.  A Verlet skin inflates the cell
    # to radius + skin (the 3x3 stencil must cover the true radius from
    # positions up to skin/2 stale).
    radius = 4.0
    extent = max(64.0, float(np.sqrt(n / 0.4)))
    cell = radius + args.skin if args.skin > 0.0 else 4.0
    width = max(1, int(extent / cell))
    width -= width % args.shards  # slab-divisible
    extent = width * cell
    # +8/+4 margin over the bench sizing: auto_bucket targets <0.1%
    # drops, but WHICH rows drop depends on within-cell order, which
    # differs between the two paths — zero drops makes parity exact
    bucket = auto_bucket(n, width) + 8
    att_bucket = auto_bucket(max(1, n // 30), width, lo=4, align=2) + 4
    geom = SpatialGeom(
        extent=extent, cell_size=cell, width=width, n_shards=args.shards,
        bucket=bucket, att_bucket=att_bucket, radius=radius,
        mig_budget=max(1024, n // 64), speed=1.0, attack_period=30,
        skin=args.skin,
    )

    rng = np.random.default_rng(42)
    pos = rng.uniform(1.0, extent - 1.0, (n, 2)).astype(np.float32)
    hp = np.full(n, 1000, np.int32)
    atk = rng.integers(5, 20, n).astype(np.int32)
    camp = (np.arange(n) % 2).astype(np.int32)

    out = {
        "metric": "spatial_vs_global_sharded_combat",
        "entities": n,
        "shards": args.shards,
        "ticks": args.ticks,
        "geometry": {
            "width": width, "slab_h": geom.slab_h, "bucket": bucket,
            "att_bucket": att_bucket,
        },
        "unit": "ms/tick (virtual CPU mesh - structure, not chip truth)",
    }

    # -- spatial ----------------------------------------------------------
    world = SpatialWorld(geom)
    world.place(pos, hp, atk, camp)
    t0 = time.perf_counter()
    world.step()  # compile + first tick
    out["spatial_compile_plus_first_tick_s"] = round(
        time.perf_counter() - t0, 2
    )
    t0 = time.perf_counter()
    world.step(args.ticks)
    out["spatial_tick_ms"] = round(
        1000 * (time.perf_counter() - t0) / args.ticks, 1
    )
    out["spatial_stats_last"] = {
        k: int(v) for k, v in zip(
            ("migrated", "mig_overflow", "mig_dropped", "misplaced",
             "vic_dropped", "att_dropped"),
            world.stats_last.sum(axis=0),
        )
    }
    if args.skin > 0.0:
        out["verlet"] = {
            "skin": args.skin,
            "rebuilds": world.rebuilds_total,
            "reuses": world.reuses_total,
        }
    sp_hp_total = sum(h for _, _, h in world.gather().values())
    spatial_ticks_total = world.tick_count
    if args.skip_global:
        out["hp_total_spatial"] = int(sp_hp_total)
        out["global"] = "skipped"
        print(json.dumps(out))
        return

    # -- global (entity-axis sharding, XLA-partitioned sort) --------------
    mesh = make_mesh(args.shards)
    row = NamedSharding(mesh, P("shard"))
    gid = jax.device_put(jnp.arange(n, dtype=jnp.int32), row)
    active = jax.device_put(jnp.ones(n, bool), row)
    posj = jax.device_put(jnp.asarray(pos), row)
    hpj = jax.device_put(jnp.asarray(hp), row)
    diedj = jax.device_put(jnp.full(n, -1, jnp.int32), row)
    atkj = jax.device_put(jnp.asarray(atk), row)
    campj = jax.device_put(jnp.asarray(camp), row)

    step = jax.jit(
        lambda p, h, dd, t: reference_step(geom, p, h, atkj, campj, gid,
                                           dd, active, t)
    )
    t0 = time.perf_counter()
    posj, hpj, diedj = step(posj, hpj, diedj, jnp.int32(0))
    jax.block_until_ready(hpj)
    out["global_compile_plus_first_tick_s"] = round(
        time.perf_counter() - t0, 2
    )
    t0 = time.perf_counter()
    for t in range(1, args.ticks + 1):
        posj, hpj, diedj = step(posj, hpj, diedj, jnp.int32(t))
    jax.block_until_ready(hpj)
    out["global_tick_ms"] = round(
        1000 * (time.perf_counter() - t0) / args.ticks, 1
    )

    # -- cross-check ------------------------------------------------------
    for t in range(args.ticks + 1, spatial_ticks_total):
        posj, hpj, diedj = step(posj, hpj, diedj, jnp.int32(t))
    # int64 host sum: int32 device accumulation wraps above ~2.1B total
    # HP (the 4M ladder exceeds it)
    gl_hp_total = int(np.asarray(hpj).astype(np.int64).sum())
    out["hp_total_spatial"] = int(sp_hp_total)
    out["hp_total_global"] = gl_hp_total
    out["parity"] = bool(sp_hp_total == gl_hp_total)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
