#!/bin/bash
# Round-5 chip-time harvester: the axon TPU tunnel comes and goes (it was
# up 01:01-01:09 UTC on 2026-07-31, long enough for one 100k capture,
# then died mid-1M).  This loop probes every ~4 min and, the moment the
# chip answers, burns down the capture queue below in priority order.
# Each item is stamped in $STAMPS so a restart never repeats finished
# work.  Only ONE process may hold the TPU: while an item runs, the loop
# is that process.
#
# Usage: nohup bash scripts/tpu_harvest.sh >/tmp/harvest.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
STAMPS=/tmp/tpu_harvest_stamps
mkdir -p "$STAMPS" bench_runs

# Cooperative handoff with bench.py (the driver's end-of-round run):
# bench raises YIELD_FLAG (its pid inside) when it wants the chip; we
# finish the item in flight, then WAIT here instead of being SIGTERMed
# mid-capture.  While an item runs we hold HOLDER_FLAG (our pid) so the
# bench knows to wait for it.  Stale flags (dead pids) are cleared on
# both sides so a crashed peer never wedges the protocol.
YIELD_FLAG=/tmp/nf_tpu_yield
HOLDER_FLAG=/tmp/nf_tpu_holder
trap 'rm -f "$HOLDER_FLAG"' EXIT

wait_for_clearance() {
  while [ -e "$YIELD_FLAG" ]; do
    local yp
    yp=$(cat "$YIELD_FLAG" 2>/dev/null)
    if [ -n "$yp" ] && ! kill -0 "$yp" 2>/dev/null; then
      # flag owner died without cleanup — a stale flag must not starve
      # the harvest forever
      rm -f "$YIELD_FLAG"
      break
    fi
    echo "[$(date -u +%H:%M:%S)] yielding TPU to pid ${yp:-?}"
    sleep 15
  done
}

probe() {
  timeout 110 python -c "import jax; d=jax.devices(); assert d[0].platform!='cpu'; import jax.numpy as jnp; print(jax.jit(lambda x:x+1)(jnp.zeros(4))[0])" >/dev/null 2>&1
}

# run <name> <timeout_s> <cmd...>  — runs once, stamps on success (a JSON
# line in the output counts as success for bench items).
run_item() {
  local name=$1 tmo=$2; shift 2
  [ -e "$STAMPS/$name" ] && return 0
  wait_for_clearance
  echo "$$" > "$HOLDER_FLAG"
  echo "[$(date -u +%H:%M:%S)] START $name"
  timeout "$tmo" "$@" > "/tmp/harvest_$name.out" 2>&1
  local rc=$?
  rm -f "$HOLDER_FLAG"
  # success = exit 0 + a JSON/marker line that is NOT an error payload
  # (bench.py catches exceptions and emits {"metric":..., "error":...}
  # with exit 0 — stamping that would archive a dead-tunnel artifact)
  if [ $rc -eq 0 ] && grep -q '"metric"\|"profile"\|"passes"\|PROBE_DONE' "/tmp/harvest_$name.out" \
     && ! grep -o '^{.*}$' "/tmp/harvest_$name.out" | tail -1 | grep -q '"error"'; then
    touch "$STAMPS/$name"
    echo "[$(date -u +%H:%M:%S)] DONE $name"
    return 0
  fi
  echo "[$(date -u +%H:%M:%S)] FAIL $name rc=$rc (tail):"
  tail -2 "/tmp/harvest_$name.out"
  return 1
}

save_json() { # save_json <name> <dest>  — extract last JSON line
  grep -o '^{.*}$' "/tmp/harvest_$1.out" | tail -1 > "$2" && echo "saved $2"
}

while :; do
  wait_for_clearance
  if ! probe; then
    echo "[$(date -u +%H:%M:%S)] tunnel down"
    sleep 230
    continue
  fi
  echo "[$(date -u +%H:%M:%S)] tunnel UP — harvesting"

  # 0. HEAD OF QUEUE: counting-sort binning A/B (NF_BINNING, ISSUE 5) at
  #    100k and 1M — the first tunnel return-window measures the new
  #    builder against the argsort path.  Baselines pin NF_BINNING=sort
  #    explicitly: bench.py applies bench_runs/tuning.json via setdefault
  #    on on-chip runs, so if a previous decide_tuning pass ever elected
  #    "count", an unpinned baseline would silently run count too and
  #    the A/B would compare count against itself.
  run_item b100k_r07 900 env NF_BINNING=sort python -u bench.py --entities 100000 --ticks 90 --platform tpu \
    && save_json b100k_r07 bench_runs/r07_tpu_100k.json
  run_item b100k_count 900 env NF_BINNING=count python -u bench.py --entities 100000 --ticks 90 --platform tpu \
    && save_json b100k_count bench_runs/r07_tpu_100k_count.json
  run_item b1m_r07 1800 env NF_BINNING=sort python -u bench.py --entities 1000000 --ticks 90 --platform tpu \
    && save_json b1m_r07 bench_runs/r07_tpu_1m.json
  run_item b1m_count 1800 env NF_BINNING=count python -u bench.py --entities 1000000 --ticks 90 --platform tpu \
    && save_json b1m_count bench_runs/r07_tpu_1m_count.json

  # 1. honest 100k re-capture (new reconcile-free windowed sampler)
  run_item b100k 900 python -u bench.py --entities 100000 --ticks 90 --platform tpu \
    && save_json b100k bench_runs/r05_tpu_100k_v2.json

  # 2. the headline: 1M fused tick (single-compile bench now)
  run_item b1m 1800 python -u bench.py --entities 1000000 --ticks 90 --platform tpu \
    && save_json b1m bench_runs/r05_tpu_1m.json

  # 3. per-phase attribution at 1M (where do the 120 ms go)
  run_item prof1m 1800 python -u scripts/profile_tick.py --entities 1000000 --iters 5 \
    && grep -o '^{.*}$' /tmp/harvest_prof1m.out | tail -1 > bench_runs/r05_profile_1m.json

  # 3b. isolated per-pass timings at 1M (sort vs build vs fold vs scatter —
  #     arbitrates docs/ROOFLINE.md's suspects independent of phase nesting)
  if run_item passes1m 1800 python -u scripts/profile_passes.py --entities 1000000 --reps 10; then
    grep -o '^{.*}$' /tmp/harvest_passes1m.out | tail -1 > bench_runs/r05_passes_1m.json
  else
    # salvage partial pass timings (profile_passes reprints the JSON
    # after every pass) WITHOUT stamping, so a retry still completes it
    grep -o '^{.*}$' /tmp/harvest_passes1m.out 2>/dev/null | tail -1 \
      > /tmp/passes_partial.$$ && [ -s /tmp/passes_partial.$$ ] \
      && mv /tmp/passes_partial.$$ bench_runs/r05_passes_1m_partial.json
    rm -f /tmp/passes_partial.$$
  fi

  # 3c. op-level xplane trace of the fused tick (offline analysis)
  run_item trace1m 1200 python -u scripts/capture_trace.py --entities 1000000 --ticks 3

  # 4. radix-sort A/B at 1M (docs/ROOFLINE.md prime suspect)
  run_item b1m_radix 1800 env NF_RADIX=1 python -u bench.py --entities 1000000 --ticks 90 --platform tpu \
    && save_json b1m_radix bench_runs/r05_tpu_1m_radix.json

  # 4b. 4-way-digit radix variant (half the irregular scatters of NF_RADIX=1)
  run_item b1m_radix2 1800 env NF_RADIX=2 python -u bench.py --entities 1000000 --ticks 90 --platform tpu \
    && save_json b1m_radix2 bench_runs/r05_tpu_1m_radix2.json

  # 5. Pallas fused fold A/B at 1M
  run_item b1m_pallas 1800 env NF_PALLAS=1 python -u bench.py --entities 1000000 --ticks 90 --platform tpu \
    && save_json b1m_pallas bench_runs/r05_tpu_1m_pallas.json

  # 5b. lane-aligned Pallas variant (W=395 is not a 128 multiple; if
  #     Mosaic rejects or tiles the unaligned kernel poorly, this one
  #     pads W to 512 with masked ghost cells)
  run_item b1m_pallas_al 1800 env NF_PALLAS=1 NF_PALLAS_ALIGN=128 python -u bench.py --entities 1000000 --ticks 90 --platform tpu \
    && save_json b1m_pallas_al bench_runs/r05_tpu_1m_pallas_aligned.json

  # 5d. fused table-free neighborhood engine A/B (NF_PALLAS=2, r11): the
  #     100k shape fits the per-core VMEM budget outright; the 1M shape
  #     documents whichever regime the chip exposes — fused if the bank
  #     fits, or the sanctioned fallback (~baseline tick + a nonzero
  #     nf_pallas_fallback_total in the capture's metrics).  Either way
  #     decide_tuning only promotes a measured win past the margin.
  run_item b100k_pallas2 900 env NF_PALLAS=2 python -u bench.py --entities 100000 --ticks 90 --platform tpu \
    && save_json b100k_pallas2 bench_runs/r11_tpu_100k_pallas2.json
  run_item b1m_pallas2 1800 env NF_PALLAS=2 python -u bench.py --entities 1000000 --ticks 90 --platform tpu \
    && save_json b1m_pallas2 bench_runs/r11_tpu_1m_pallas2.json

  # 5c. round-6 baseline + Verlet-skin A/B at 1M (ops/verlet.py): the
  #     skin trades argsort rate against bucket inflation, so the winner
  #     is elected from measurement (decide_tuning.py -> NF_VERLET_SKIN)
  run_item b1m_r06 1800 python -u bench.py --entities 1000000 --ticks 90 --platform tpu \
    && save_json b1m_r06 bench_runs/r06_tpu_1m.json
  for skin in 1 2 4; do
    run_item b1m_verlet$skin 1800 env NF_VERLET_SKIN=$skin python -u bench.py \
        --entities 1000000 --ticks 90 --platform tpu \
      && save_json b1m_verlet$skin bench_runs/r06_tpu_1m_verlet$skin.json
  done

  # promote measured winners into bench_runs/tuning.json (re-runs are
  # idempotent; no-op until the baseline 1M capture exists) so the
  # driver's end-of-round bench uses the fastest measured engine flags
  python -u scripts/decide_tuning.py || true

  # 6. served path on chip: tick + diff flush + interest fan-out, 500 sessions
  run_item serve100k 1800 python -u bench.py --entities 100000 --ticks 30 --served \
      --sessions 500 --interest-radius 8.0 --platform tpu \
    && save_json serve100k bench_runs/r05_tpu_served_100k_interest.json

  # 7. served path, group-broadcast mode (reference-parity fan-out)
  run_item serve100k_bcast 1800 python -u bench.py --entities 100000 --ticks 30 --served \
      --sessions 500 --platform tpu \
    && save_json serve100k_bcast bench_runs/r05_tpu_served_100k.json

  # 8. 250k rung (scaling point between the two captures)
  run_item b250k 1200 python -u bench.py --entities 250000 --ticks 90 --platform tpu \
    && save_json b250k bench_runs/r05_tpu_250k.json

  # 9. BASELINE config 3 (500k, AOI under combat load) and config 2
  #    (100k random-walk + regen, no combat) at their own shapes
  run_item b500k 1500 python -u bench.py --entities 500000 --ticks 90 --platform tpu \
    && save_json b500k bench_runs/r05_tpu_500k.json
  run_item b100k_walk 900 python -u bench.py --entities 100000 --ticks 90 --no-combat --platform tpu \
    && save_json b100k_walk bench_runs/r05_tpu_100k_nocombat.json

  # 10. elastic reshard on chip (ISSUE 17 r10): grow 2->4, drain->3
  #     over REAL devices.  Guarded: the ladder needs >=4 chips, and a
  #     v4-8 tunnel sometimes exposes a single-chip slice — probe the
  #     device count first so the item fails fast without burning the
  #     window (unstamped items retry next pass).
  if timeout 110 python -c "import jax; assert len(jax.devices())>=4" >/dev/null 2>&1; then
    run_item reshard4 1800 python -u bench.py --reshard 4 --platform tpu \
        --mig-entities 12000,60000 --mig-budgets 512,2048 \
      && save_json reshard4 bench_runs/r10_elastic_tpu.json
  else
    echo "[$(date -u +%H:%M:%S)] SKIP reshard4 — backend exposes <4 devices"
  fi

  # 11. many-worlds rooms ladder on chip (ISSUE 19 r12): thousands of
  #     independent rooms vmapped as one batch, room-major sharded.
  #     Guarded like reshard: the mesh width adapts to what the tunnel
  #     actually exposes (1-chip slices are fine — the rooms axis still
  #     batches, it just doesn't shard).
  NDEV=$(timeout 110 python -c "import jax; print(len(jax.devices()))" 2>/dev/null || echo 0)
  if [ "$NDEV" -ge 1 ]; then
    run_item rooms 1800 python -u bench.py --rooms "$NDEV" --platform tpu \
        --rooms-count 64,256,1024 --rooms-entities 64 \
      && save_json rooms bench_runs/r12_rooms_tpu.json
  else
    echo "[$(date -u +%H:%M:%S)] SKIP rooms — no devices exposed"
  fi

  # 12. K-tick trains on chip (ISSUE 20 r13): the 100k tick under an
  #     8-tick lax.scan megadispatch (tick_ms amortized per tick), vs
  #     the item-0 baseline decide_tuning already compares against —
  #     NF_TICK_TRAIN=8 is promoted only on a measured >3% win.  The
  #     rooms arm re-runs the many-worlds ladder with trains so the
  #     flagship 256-room point gets its train number on real chips.
  run_item b100k_train8 900 python -u bench.py --entities 100000 --ticks 96 --train 8 --platform tpu \
    && save_json b100k_train8 bench_runs/r13_tpu_100k_train8.json
  if [ "$NDEV" -ge 1 ]; then
    run_item rooms_train8 1800 python -u bench.py --rooms "$NDEV" --platform tpu \
        --rooms-count 64,256,1024 --rooms-entities 64 --train 8 \
      && save_json rooms_train8 bench_runs/r13_rooms_tpu_train8.json
  else
    echo "[$(date -u +%H:%M:%S)] SKIP rooms_train8 — no devices exposed"
  fi

  n_done=$(ls "$STAMPS" | wc -l)
  if [ "$n_done" -ge 27 ]; then
    echo "[$(date -u +%H:%M:%S)] queue drained — exiting"
    exit 0
  fi
  sleep 20
done
