#!/usr/bin/env python
"""Frame-observatory smoke: end-to-end latency attribution over a served
cluster, plus the replay-identity proof that tracing is free of state.

    JAX_PLATFORMS=cpu python scripts/pipeline_smoke.py

Boots the five-role LocalCluster with NF_TRACE_SAMPLE=1 (every session
traced) and a journaling game role, walks a GameClient through the full
login pipeline, drives movement until traced frames round-trip, and
asserts:

- FRAME_TRACE sidecars flow game → proxy → client and the acks close
  the loop (RTT + proxy-relay histograms fill on the game role);
- the StageClock waterfall (tick/harvest/interest/encode/send/other)
  sums to the frame wall time within tolerance;
- the master's /pipeline endpoint serves well-formed JSON: per-game
  stage stats + trace counters and NTP-style clock offsets;
- a multi-process Perfetto merge (game + proxy tracers, distinct pids,
  clock offsets applied) yields one well-formed chrome-trace doc;
- the journal NEVER contains a trace-sidecar event, and an offline
  replay with tracing DISABLED reproduces every per-tick state digest
  bit for bit — observability on vs off cannot change the simulation.

Exits 0 on success — tests/test_pipeline.py wires this into CI.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

TRACED_ACKS = 3  # acked round trips before we call the loop closed


def run(tmpdir) -> dict:
    """Run the whole scenario; returns {check name: bool}."""
    from noahgameframe_tpu.client import GameClient
    from noahgameframe_tpu.net.defines import TRACE_MSG_IDS
    from noahgameframe_tpu.net.roles.cluster import LocalCluster
    from noahgameframe_tpu.replay import replay_journal
    from noahgameframe_tpu.replay.journal import (
        JournalReader,
        REC_EVENT,
        decode_event,
    )
    from noahgameframe_tpu.telemetry.pipeline import merge_chrome_traces

    jdir = Path(tmpdir) / "journal"
    checks = {}
    old_env = os.environ.get("NF_TRACE_SAMPLE")
    os.environ["NF_TRACE_SAMPLE"] = "1"  # read at GameRole construction
    try:
        cluster = LocalCluster(
            http_port=0, game_kwargs={"journal_dir": jdir}
        )
    finally:
        if old_env is None:
            os.environ.pop("NF_TRACE_SAMPLE", None)
        else:
            os.environ["NF_TRACE_SAMPLE"] = old_env
    game, proxy, master = cluster.game, cluster.proxy, cluster.master
    # span capture for the Perfetto merge below
    game.telemetry.tracer.enabled = True
    proxy.telemetry.tracer.enabled = True
    cli = GameClient("observer")
    try:
        cluster.start(timeout=30)
        checks["cluster wired"] = True
        cli.connect("127.0.0.1", cluster.login.config.port)

        def pump(cond, t=15.0):
            return cluster.pump_until(cond, extra=cli.execute, timeout=t)

        ok = pump(lambda: cli.connected)
        cli.login()
        ok = ok and pump(lambda: cli.logged_in)
        cli.request_world_list()
        ok = ok and pump(lambda: cli.worlds)
        cli.connect_world(cli.worlds[0].server_id)
        ok = ok and pump(lambda: cli.world_grant is not None)
        cli.connect_proxy()
        ok = ok and pump(lambda: cli.connected)
        cli.verify_key()
        ok = ok and pump(lambda: cli.key_verified)
        cli.select_server(game.config.server_id)
        ok = ok and pump(lambda: cli.server_selected)
        cli.create_role("Obs")
        ok = ok and pump(lambda: cli.roles)
        cli.enter_game("Obs")
        ok = ok and pump(lambda: cli.entered)
        checks["client entered world"] = ok

        # keep the avatar moving so every frame has diffs to flush (and
        # therefore a trace sidecar trailing the sync traffic)
        step = [0]

        def stir():
            cli.execute()
            step[0] += 1
            if step[0] % 40 == 0 and cli.entered:
                cli.move_to(float(step[0] % 500), 100.0)

        checks["trace loop closed"] = cluster.pump_until(
            lambda: game.trace_acked >= TRACED_ACKS, extra=stir, timeout=30
        )
        checks["client saw stamped sidecars"] = any(
            t["proxy_relay_ms"] is not None for t in cli.traces
        )
        checks["rtt histogram filled"] = game._trace_rtt_hist.count > 0
        checks["relay histogram filled"] = game._trace_relay_hist.count > 0
        checks["proxy counted relays"] = proxy.traces_relayed >= TRACED_ACKS
        checks["proxy per-opcode relay latency"] = bool(
            proxy.games.counters.relay_ns
        )

        # ---- the waterfall sums to the frame wall time
        ps = game.pipeline_stats()
        checks["stage clock saw frames"] = ps["frames"] > 0
        total = sum(ps["last_ms"].values())
        # exact by construction (explicit "other" bucket); rounding of
        # up to 6 stages at 4 decimals bounds the drift
        checks["waterfall sums to frame latency"] = (
            abs(total - ps["last_wall_ms"]) <= 0.01
        )
        checks["tick stage attributed"] = "tick" in ps["stages"]
        checks["encode stage attributed"] = "encode" in ps["stages"]

        # ---- /pipeline over real HTTP
        checks["heartbeats carried pipeline blob"] = cluster.pump_until(
            lambda: master.pipeline_status()["games"]
            and "frames" in (master.pipeline_status()["games"][0]
                             .get("pipeline") or {}),
            extra=cli.execute, timeout=15,
        )
        # urlopen blocks, so the cluster needs a background pump while
        # the request is in flight (same pattern as tests/test_roles.py)
        import threading
        import time as _t

        stop = threading.Event()

        def _bg():
            while not stop.is_set():
                cluster.execute()
                _t.sleep(0.002)

        th = threading.Thread(target=_bg, daemon=True)
        th.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{master.http.port}/pipeline", timeout=5
            ) as r:
                pipe = json.loads(r.read().decode())
        finally:
            stop.set()
            th.join(timeout=2)
        checks["/pipeline well-formed"] = (
            isinstance(pipe.get("clock_offsets_ns"), dict)
            and isinstance(pipe.get("games"), list)
            and pipe["games"]
            and pipe["games"][0]["pipeline"]["frames"] > 0
        )
        checks["clock offsets estimated"] = any(
            k.startswith("game") for k in pipe["clock_offsets_ns"]
        )

        # ---- multi-process Perfetto merge with aligned clocks
        off = pipe["clock_offsets_ns"].get(
            f"proxy{proxy.config.server_id}", 0) / 1e3
        merged = merge_chrome_traces(
            [game.telemetry.tracer.chrome_trace(
                process_name=f"game{game.config.server_id}", pid=1),
             proxy.telemetry.tracer.chrome_trace(
                process_name=f"proxy{proxy.config.server_id}", pid=2)],
            offsets_us=[0.0, off],
        )
        evs = merged["traceEvents"]
        checks["perfetto merge well-formed"] = (
            merged.get("displayTimeUnit") == "ms"
            and all("pid" in e and "ph" in e for e in evs)
            and {e["pid"] for e in evs} == {1, 2}
        )
    finally:
        cli.close()
        cluster.shut()

    # ---- trace traffic never reaches the journal
    sidecars = sum(
        1 for rec_type, body in JournalReader(jdir)
        if rec_type == REC_EVENT and decode_event(body)[3] in TRACE_MSG_IDS
    )
    checks["journal free of trace sidecars"] = sidecars == 0

    # ---- replay with tracing OFF reproduces the traced run bit for bit
    old = os.environ.get("NF_TRACE_SAMPLE")
    os.environ["NF_TRACE_SAMPLE"] = "0"
    try:
        rep = replay_journal(jdir)
    finally:
        if old is None:
            os.environ.pop("NF_TRACE_SAMPLE", None)
        else:
            os.environ["NF_TRACE_SAMPLE"] = old
    checks["replayed ticks"] = rep.ticks_replayed > 0
    checks["replay bit-identical with tracing off"] = rep.ok
    return checks


def main() -> int:
    with tempfile.TemporaryDirectory() as tmpdir:
        checks = run(tmpdir)
    failed = [name for name, ok in checks.items() if not ok]
    for name, ok in checks.items():
        print(f"  {'ok  ' if ok else 'FAIL'} {name}")
    if failed:
        print(f"PIPELINE SMOKE FAILED: {failed}")
        return 1
    print(f"PIPELINE SMOKE OK: {len(checks)} checks")
    return 0


if __name__ == "__main__":
    sys.exit(main())
