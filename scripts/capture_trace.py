"""Capture a jax.profiler trace of the fused 1M tick on the live
backend and tar it into bench_runs/ for offline op-level analysis
(docs/ROOFLINE.md step 1 — the per-pass profiler ranks passes, the
xplane trace attributes time op by op inside them).

Usage: python scripts/capture_trace.py [--entities 1000000] [--ticks 3]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tarfile
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--entities", type=int, default=1_000_000)
    ap.add_argument("--ticks", type=int, default=3)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "..", "bench_runs", "r05_trace_1m.tar.gz"))
    args = ap.parse_args()

    from noahgameframe_tpu.utils.platform import init_compile_cache

    os.environ.setdefault("NF_COMPILE_CACHE", "/tmp/nf_xla_cache")
    init_compile_cache()

    import jax

    from noahgameframe_tpu.game import build_benchmark_world

    world = build_benchmark_world(args.entities, combat=True, seed=42)
    k = world.kernel
    k.run_device(1)  # compile outside the trace
    jax.block_until_ready(k.state.classes["NPC"].i32)

    tmp = tempfile.mkdtemp(prefix="nf_trace_")
    t0 = time.perf_counter()
    with jax.profiler.trace(tmp):
        for _ in range(args.ticks):
            k.run_device(1, reconcile=False)
        jax.block_until_ready(k.state.classes["NPC"].i32)
    elapsed = time.perf_counter() - t0

    with tarfile.open(args.out, "w:gz") as tar:
        tar.add(tmp, arcname="trace")
    n_files = sum(len(fs) for _, _, fs in os.walk(tmp))
    print(json.dumps({
        "metric": "trace_capture",
        "entities": args.entities,
        "ticks": args.ticks,
        "traced_wall_s": round(elapsed, 3),
        "files": n_files,
        "archive": os.path.basename(args.out),
        "bytes": os.path.getsize(args.out),
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
