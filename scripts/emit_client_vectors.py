"""Regenerate the committed Unity client-binding artifacts.

Writes clients/unity/: the generated C# message binding (NFMsg.cs), the
golden wire vectors (NFMsgGolden.tsv, one deterministic encode of every
declared message by the protoc-verified Python codec) and the replay
harness (NFMsgGoldenTest.cs).  Run from the repo root:

    python scripts/emit_client_vectors.py
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from noahgameframe_tpu.tools.emit_cs_sdk import emit_cs
from noahgameframe_tpu.tools.golden_vectors import emit_cs_harness, emit_vectors


def main() -> None:
    out = pathlib.Path(__file__).resolve().parent.parent / "clients" / "unity"
    out.mkdir(parents=True, exist_ok=True)
    (out / "NFMsg.cs").write_text(emit_cs())
    (out / "NFMsgGolden.tsv").write_text(emit_vectors())
    (out / "NFMsgGoldenTest.cs").write_text(emit_cs_harness())
    for p in sorted(out.iterdir()):
        print(p, p.stat().st_size, "bytes")


if __name__ == "__main__":
    main()
