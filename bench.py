"""Benchmark entry point: entities ticked per second on one chip.

Runs the BASELINE config-2/4 style workload — N NPCs random-walking,
regenerating, and resolving AoE combat through the grid-AOI pipeline —
as the fully-fused device tick (`Kernel.run_device`), and prints ONE JSON
line:

    {"metric": "entities_ticked_per_sec_per_chip", "value": ..., "unit":
     "entities*ticks/s", "vs_baseline": ...}

`vs_baseline` is value / (1M entities * 30 Hz), i.e. 1.0 == the north-star
"1M NPCs at 30 Hz on one chip's share of a v4-8" (BASELINE.json).  The
reference itself publishes no numbers (BASELINE.md): its design point is
5000 entities/process at <=1 kHz host loop.
"""

from __future__ import annotations

import argparse
import json
import time

NORTH_STAR_RATE = 1_000_000 * 30  # entity-ticks/sec


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--entities", type=int, default=200_000)
    ap.add_argument("--ticks", type=int, default=90)
    ap.add_argument("--no-combat", action="store_true")
    args = ap.parse_args()

    import jax

    from noahgameframe_tpu.game import build_benchmark_world

    n = args.entities
    world = build_benchmark_world(n, combat=not args.no_combat, seed=42)
    k = world.kernel

    # compile + warm up the fused loop with the SAME trip count (run_device
    # caches per n; a different warmup n would leave compile time in the
    # timed region)
    k.run_device(args.ticks)
    jax.block_until_ready(k.state.classes["NPC"].i32)

    t0 = time.perf_counter()
    k.run_device(args.ticks)
    jax.block_until_ready(k.state.classes["NPC"].i32)
    dt = time.perf_counter() - t0

    ticks_per_s = args.ticks / dt
    rate = n * ticks_per_s
    print(
        json.dumps(
            {
                "metric": "entities_ticked_per_sec_per_chip",
                "value": round(rate, 1),
                "unit": "entity-ticks/s",
                "vs_baseline": round(rate / NORTH_STAR_RATE, 4),
                "detail": {
                    "entities": n,
                    "ticks": args.ticks,
                    "elapsed_s": round(dt, 4),
                    "ticks_per_s": round(ticks_per_s, 2),
                    "tick_ms": round(1000 * dt / args.ticks, 3),
                    "device": str(jax.devices()[0]),
                    "combat": not args.no_combat,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
