"""Benchmark entry point: entities ticked per second on one chip.

Runs the BASELINE config-2/4 style workload — N NPCs random-walking,
regenerating, and resolving AoE combat through the grid-AOI pipeline —
as the fully-fused device tick (`Kernel.run_device`), and prints ONE JSON
line:

    {"metric": "entities_ticked_per_sec_per_chip", "value": ..., "unit":
     "entities*ticks/s", "vs_baseline": ...}

`vs_baseline` is value / (1M entities * 30 Hz), i.e. 1.0 == the north-star
"1M NPCs at 30 Hz on one chip's share of a v4-8" (BASELINE.json).  The
reference itself publishes no numbers (BASELINE.md): its design point is
5000 entities/process at <=1 kHz host loop.

Robustness contract (the driver must always get a parseable line):
- The accelerator backend ("axon" tunnelled TPU) is probed in a
  SUBPROCESS with a timeout, retried once; on failure the bench falls
  back to the CPU platform and records the probe error in `detail`.
- Any exception in the bench itself still emits a JSON line with an
  `"error"` key and value 0.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

NORTH_STAR_RATE = 1_000_000 * 30  # entity-ticks/sec

_PROBE_CODE = (
    "import jax; d = jax.devices(); "
    "assert d[0].platform != 'cpu', 'cpu-only'; "
    "import jax.numpy as jnp; "
    "print(jax.jit(lambda x: x + 1)(jnp.zeros(8))[0]); "
    "print('PROBE_OK', d[0])"
)


def _probe_accelerator(timeout_s: float) -> tuple[bool, str]:
    """Try to initialise the accelerator backend in a throwaway process.

    The axon TPU plugin can hang forever inside PJRT client init when the
    tunnel is unreachable (round-1 failure mode) — a subprocess + timeout
    is the only safe probe."""
    try:
        r = subprocess.run(
            [sys.executable, "-u", "-c", _PROBE_CODE],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, f"probe timeout after {timeout_s:.0f}s (backend init hang)"
    except Exception as e:  # noqa: BLE001
        return False, f"probe spawn failed: {e}"
    if r.returncode == 0 and "PROBE_OK" in r.stdout:
        return True, r.stdout.strip().splitlines()[-1]
    tail = (r.stderr or r.stdout or "").strip().splitlines()[-3:]
    return False, f"probe rc={r.returncode}: " + " | ".join(tail)


def _force_cpu() -> None:
    from noahgameframe_tpu.utils.platform import force_cpu

    force_cpu()


# Cooperative TPU handoff with scripts/tpu_harvest.sh: the bench raises
# the YIELD flag (its pid inside) before probing; the harvester checks it
# between queue items and pauses while it exists, and advertises an
# in-flight capture by holding the HOLDER flag (its pid inside).  The
# bench waits for the holder to clear instead of SIGTERMing a capture
# mid-flight (_evict_harvester stays as the timeout fallback only).
YIELD_FLAG = "/tmp/nf_tpu_yield"
HOLDER_FLAG = "/tmp/nf_tpu_holder"


def _clear_yield_flag() -> None:
    """Remove OUR yield flag at exit (never another bench's)."""
    try:
        with open(YIELD_FLAG) as f:
            if int(f.read().strip() or 0) != os.getpid():
                return
    except (OSError, ValueError):
        return
    try:
        os.remove(YIELD_FLAG)
    except OSError:
        pass


def _holder_pid():
    """Pid in the harvester's holder flag, or None when no capture is
    registered (missing/garbage file == free)."""
    try:
        with open(HOLDER_FLAG) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def _request_tpu_yield(wait_s: float = 120.0) -> None:
    """Ask a running harvester to pause instead of killing it: raise the
    yield flag, then wait (bounded) for any in-flight capture to finish
    and release the holder flag.  A holder whose pid is dead is a stale
    flag from a crashed capture — clear it and proceed.  Only if the
    holder outlives the wait does the old SIGTERM eviction fire."""
    import atexit

    try:
        with open(YIELD_FLAG, "w") as f:
            f.write(str(os.getpid()))
        atexit.register(_clear_yield_flag)
    except OSError:
        _evict_harvester()
        return
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        holder = _holder_pid()
        if holder is None:
            return
        if not _pid_alive(holder):
            try:
                os.remove(HOLDER_FLAG)
            except OSError:
                pass
            return
        time.sleep(2.0)
    print(f"# tpu holder pid {_holder_pid()} ignored yield for "
          f"{wait_s:.0f}s; evicting", file=sys.stderr)
    _evict_harvester()


def _evict_harvester() -> None:
    """Kill any in-round capture harvester (scripts/tpu_harvest.sh) and
    its process group.  Only ONE process can hold the tunnelled TPU: if
    the harvester (or a capture it spawned) holds the claim when the
    driver's end-of-round bench probes, the probe hangs to timeout and
    the official artifact falls back to CPU.  Auto mode IS the driver
    invocation; the harvester's own children run --platform tpu and
    never reach this.  FALLBACK path: _request_tpu_yield's cooperative
    lockfile handoff is tried first."""
    import signal

    try:
        r = subprocess.run(
            # anchored: match the harvester SHELL, not any process that
            # merely mentions the path (an editor/tail on the script)
            ["pgrep", "-f", r"bash .*scripts/tpu_harvest\.sh"],
            capture_output=True, text=True, timeout=10,
        )
        victims = []
        harvester_pgids = set()
        my_pgid = os.getpgid(0)
        for line in (r.stdout or "").split():
            try:
                pid = int(line)
                pgid = os.getpgid(pid)
                harvester_pgids.add(pgid)
                if pgid == my_pgid:
                    # harvester launched from OUR process group (no job
                    # control): killpg would take bench.py down with it —
                    # kill the pid alone
                    os.kill(pid, signal.SIGTERM)
                else:
                    os.killpg(pgid, signal.SIGTERM)
                victims.append(pid)
                print(f"# evicted harvester pid {pid} (pgid {pgid})",
                      file=sys.stderr)
            except (ValueError, ProcessLookupError, PermissionError):
                pass
        # the harvester's in-flight CAPTURE child is what actually holds
        # the TPU claim — kill it directly too, but ONLY if it belongs to
        # a process group a first-pass harvester was found in: a bare
        # command-line match would SIGTERM any operator-run capture or
        # profile session machine-wide
        r2 = subprocess.run(
            ["pgrep", "-f", r"python -u .*(bench\.py|profile_\w+\.py|"
                            r"capture_trace\.py) .*--platform tpu|"
                            r"python -u scripts/(profile_passes|"
                            r"profile_tick|capture_trace)\.py"],
            capture_output=True, text=True, timeout=10,
        )
        for line in (r2.stdout or "").split():
            try:
                pid = int(line)
                if pid != os.getpid() and os.getpgid(pid) in harvester_pgids:
                    os.kill(pid, signal.SIGTERM)
                    victims.append(pid)
            except (ValueError, ProcessLookupError, PermissionError):
                pass
        # wait (bounded) for the TPU claim to actually release — probing
        # while the dying capture still tears down PJRT would hang to
        # timeout exactly like the race this function exists to prevent
        deadline = time.monotonic() + 15.0
        while victims and time.monotonic() < deadline:
            victims = [p for p in victims if _pid_alive(p)]
            if victims:
                time.sleep(0.25)
    except Exception:  # noqa: BLE001 — eviction is best-effort
        pass


def _best_onchip_capture() -> dict:
    """When the official run falls back to CPU (dead tunnel), point the
    artifact at the best preserved on-chip capture so the number is
    read in context: {file, value, tick_ms, entities, captured_note}."""
    runs = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_runs")
    best: dict = {}
    try:
        for name in sorted(os.listdir(runs)):
            if not (name.endswith(".json") and "_tpu_" in name):
                continue
            try:
                with open(os.path.join(runs, name)) as f:
                    d = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            det = d.get("detail") or {}
            if d.get("error") or det.get("platform") not in ("tpu", "axon"):
                continue
            val = float(d.get("value") or 0.0)
            if val > float(best.get("value") or 0.0):
                best = {
                    "file": f"bench_runs/{name}",
                    "value": val,
                    "unit": d.get("unit"),
                    "entities": det.get("entities"),
                    "tick_ms": det.get("tick_ms"),
                }
    except OSError:
        pass
    return best


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def _overflow_gauges(world) -> tuple:
    """Run both offline overflow replays, publish them on the world's
    telemetry registry, and read the JSON values BACK from the registry —
    bench JSON and a /metrics scrape can never disagree."""
    reg = world.telemetry.registry
    g = reg.gauge(
        "nf_bench_overflow_replay",
        "offline cell-table overflow replay (max drops per tick)",
        ("side",),
    )
    g.set(_grid_overflow_max(world), side="victim")
    g.set(_att_overflow_max(world), side="attacker")
    return (
        int(reg.value("nf_bench_overflow_replay", side="victim")),
        int(reg.value("nf_bench_overflow_replay", side="attacker")),
    )


def _hist_pcts(hist) -> tuple:
    """p50/p95/p99 in ms from a registry histogram (the ONE percentile
    implementation — telemetry.registry.Histogram.percentile)."""
    return tuple(round(hist.percentile(p) * 1e3, 3) for p in (50, 95, 99))


def _costbook_detail(book, pipeline_stats=None) -> dict:
    """Compiled-cost evidence for a BENCH `detail` block: compile wall,
    recompile count+causes, HBM peak from a fresh census, per-entry
    cost — and, when the run has a StageClock waterfall, the per-stage
    achieved-vs-peak roofline fractions (CostBook x StageClock)."""
    from noahgameframe_tpu.telemetry.costbook import roofline_fold

    hbm = book.hbm_sample()
    out = {
        "compile_ms": round(book.compile_s_total * 1e3, 1),
        "compiles": book.total_compiles,
        "recompiles": book.total_recompiles,
        "recompile_causes": {
            n: dict(e.causes)
            for n, e in sorted(book.entries.items()) if e.causes
        },
        "hbm_peak_bytes": int(hbm.get("peak_bytes", 0)),
        "hbm_live_bytes": int(hbm.get("live_bytes", 0)),
        "hbm_source": hbm.get("source"),
        "entries": {n: {"compiles": e.compiles,
                        "flops": e.last.get("flops", 0.0),
                        "bytes_accessed": e.last.get("bytes_accessed", 0.0),
                        "temp_bytes": e.last.get("temp_bytes", 0)}
                    for n, e in sorted(book.entries.items())},
    }
    if pipeline_stats is not None:
        rf = roofline_fold(book, pipeline_stats)
        out["roofline"] = {
            "platform": rf["platform"],
            "provisional": rf["provisional"],
            "stages": {
                s: {"frac_of_peak_flops": round(v["frac_of_peak_flops"], 6),
                    "frac_of_peak_bytes": round(v["frac_of_peak_bytes"], 6),
                    "device_s_per_frame": v["device_s_per_frame"]}
                for s, v in rf["stages"].items()
            },
        }
    return out


def _combat_cost_probe(world) -> dict:
    """Attribute the combat fold's compiled cost to a per-engine
    CostBook entry (``combat.fold_p0/p1/p2``) from the final world
    state, OUTSIDE the timed region — so ``detail.costbook.entries``
    carries the split-vs-fused ``bytes_accessed`` the r11 A/B compares
    from the same ledger as everything else.  Probes the engine the run
    actually used (including the fused path's VMEM downgrade), one
    compile + one call; the fold math and geometry are exactly the
    combat phase's (`game/combat.py` is the source of truth)."""
    combat = getattr(world, "combat", None)
    if combat is None:
        return {}
    try:
        import jax
        import jax.numpy as jnp

        from noahgameframe_tpu.game.combat import combat_fold_xla
        from noahgameframe_tpu.ops.stencil import (
            CellSlots,
            CellTable,
            build_cell_slots_pair,
            build_cell_table_pair,
        )
        from noahgameframe_tpu.ops.stencil_pallas import (
            combat_fold_pallas,
            fused_fits_vmem,
            fused_neighborhood,
        )

        k = world.kernel
        cname = combat.class_name
        spec = k.store.spec(cname)
        cs = k.state.classes[cname]
        pos = cs.vec[:, spec.slot("Position").col, :2]
        alive = cs.alive
        cap = alive.shape[0]
        cell_size, width = combat.cell_size, combat.width
        bucket = combat.resolved_bucket(cap)
        att_bucket = combat.resolved_att_bucket(cap)
        engine = combat.resolved_engine()
        fell_back = False
        if engine == 2:
            fits, _need, _budget = fused_fits_vmem(cap, width, bucket,
                                                   att_bucket)
            if not fits:
                engine, fell_back = 0, True

        f32 = jnp.float32
        camp_f = cs.i32[:, spec.slot("Camp").col].astype(f32)
        scene_f = cs.i32[:, spec.slot("SceneID").col].astype(f32)
        group_f = cs.i32[:, spec.slot("GroupID").col].astype(f32)
        atk_f = cs.i32[:, spec.slot("ATK_VALUE").col].astype(f32)
        interval = max(1, k.schedule.ticks_of(combat.attack_period_s))
        attacking = alive & ((jnp.arange(cap) % interval) == 0)
        interp = jax.default_backend() not in ("tpu", "axon")
        book = k.costbook
        entry = f"combat.fold_p{engine}"
        radius = combat.radius

        if engine == 2:
            vic_s, att_s = build_cell_slots_pair(
                pos, alive, attacking, cell_size, width, bucket, att_bucket
            )
            bank = jnp.stack(
                [pos[:, 0], pos[:, 1], camp_f, scene_f, group_f, atk_f], -1
            )
            fold = book.wrap(
                entry,
                lambda bk, vso, aso: fused_neighborhood(
                    bk,
                    CellSlots(vso, jnp.int32(0), width, cell_size, bucket),
                    CellSlots(aso, jnp.int32(0), width, cell_size,
                              att_bucket),
                    radius, interpret=interp,
                ),
                stage="aoe",
            )
            jax.block_until_ready(fold(bank, vic_s.slot_of, att_s.slot_of))
        else:
            rows_f = jnp.arange(cap, dtype=f32)
            vic_f = jnp.stack(
                [pos[:, 0], pos[:, 1], camp_f, scene_f, group_f], -1
            )
            att_f = jnp.stack(
                [pos[:, 0], pos[:, 1], atk_f, camp_f, scene_f, group_f,
                 rows_f], -1
            )
            vt, at = build_cell_table_pair(
                pos, alive, vic_f, attacking, att_f,
                cell_size, width, bucket, att_bucket,
            )
            if engine == 1:
                fold = book.wrap(
                    entry,
                    lambda vp, vs, ap, as_: combat_fold_pallas(
                        CellTable(vp, vs, jnp.int32(0), width, cell_size,
                                  bucket),
                        CellTable(ap, as_, jnp.int32(0), width, cell_size,
                                  att_bucket),
                        radius, interpret=interp,
                    ),
                    stage="aoe",
                )
            else:
                fold = book.wrap(
                    entry,
                    lambda vp, vs, ap, as_: combat_fold_xla(
                        CellTable(vp, vs, jnp.int32(0), width, cell_size,
                                  bucket),
                        CellTable(ap, as_, jnp.int32(0), width, cell_size,
                                  att_bucket),
                        radius,
                    ),
                    stage="aoe",
                )
            jax.block_until_ready(
                fold(vt.payload, vt.slot_of, at.payload, at.slot_of)
            )
        return {"engine": engine, "vmem_fallback": fell_back,
                "entry": entry}
    except Exception as e:  # noqa: BLE001 — evidence, never a bench kill
        return {"error": f"{type(e).__name__}: {e}"}


def _grid_overflow_max(world) -> int:
    """Rebuild the combat victim cell-table from the final state once
    (outside the timed region) and report entities dropped by bucket
    overflow — silent drops were a round-1 finding.  This is exactly the
    table the combat phase builds (all alive entities, auto-sized
    buckets), so it is the real per-tick drop count, not an upper bound."""
    try:
        import jax.numpy as jnp

        from noahgameframe_tpu.ops.stencil import build_cell_table

        combat = getattr(world, "combat", None)
        if combat is None:
            return -1
        cname = combat.class_name
        store = world.kernel.store
        spec = store.spec(cname)
        cs = world.kernel.state.classes[cname]
        pos = cs.vec[:, spec.slot("Position").col, :2]
        n = pos.shape[0]
        bucket = combat.resolved_bucket(n)
        table = build_cell_table(
            pos,
            cs.alive,
            jnp.zeros((n, 0), jnp.float32),
            combat.cell_size,
            combat.width,
            bucket,
        )
        return int(table.dropped)
    except Exception:  # noqa: BLE001
        return -1


def _att_overflow_max(world) -> int:
    """Worst-phase attacker-table drop count: replay each firing residue
    of the attack timer against the final positions (the attacker
    candidate table only holds one residue class per tick under staggered
    arming — a dropped attacker is an attack that doesn't land).  Exact
    for the benchmark world (timers keep their armed phase forever:
    next_fire advances by one interval per firing)."""
    try:
        import jax
        import jax.numpy as jnp

        from noahgameframe_tpu.ops.stencil import build_cell_table

        combat = getattr(world, "combat", None)
        if combat is None:
            return -1
        k = world.kernel
        cname = combat.class_name
        spec = k.store.spec(cname)
        cs = k.state.classes[cname]
        pos = cs.vec[:, spec.slot("Position").col, :2]
        n = pos.shape[0]
        att_bucket = combat.resolved_att_bucket(n)
        slot = k.schedule.slot(cname, "Attack")
        t = cs.timers
        interval = max(1, k.schedule.ticks_of(combat.attack_period_s))
        armed = t.active[:, slot] & cs.alive
        residue = t.next_fire[:, slot] % interval

        @jax.jit
        def drops_of(p):
            mask = armed & (residue == p)
            return build_cell_table(
                pos,
                mask,
                jnp.zeros((n, 0), jnp.float32),
                combat.cell_size,
                combat.width,
                att_bucket,
            ).dropped

        return max(int(drops_of(p)) for p in range(interval))
    except Exception:  # noqa: BLE001
        return -1


def run_served(args) -> dict:
    """The SERVED path: kernel.tick() with host observation + the game
    role's full per-frame sync flush (diff fetch, message serialization,
    envelope encode, broadcast fan-out to S sessions) — the cost a real
    game server pays per frame, which run_device excludes (round-1 weak
    #4: benchmark path != served path).  Transport writes are captured
    into a byte counter instead of sockets."""
    import jax

    from noahgameframe_tpu.core.datatypes import Guid  # noqa: F401
    from noahgameframe_tpu.game import build_benchmark_world
    from noahgameframe_tpu.net.roles.base import RoleConfig
    from noahgameframe_tpu.net.roles.game import GameRole, Session
    from noahgameframe_tpu.net.wire import Ident, ident_key
    from noahgameframe_tpu.ops.stencil import binning_mode
    from noahgameframe_tpu.utils.platform import init_compile_cache

    init_compile_cache()
    n = args.entities
    # one live Player avatar per simulated session, + headroom (the
    # driver's served probe seats 500 — round-2 weak #6 follow-up: the
    # default 64-row Player bank made the probe crash at session 65)
    from noahgameframe_tpu.core.datatypes import next_pow2

    world = build_benchmark_world(
        n,
        combat=not args.no_combat,
        seed=args.seed,
        player_capacity=next_pow2(args.sessions + 8, lo=64),
    )
    role = GameRole(
        RoleConfig(6, 0, "BenchGame", "127.0.0.1", 0),
        backend="py",
        world=world,
        cross_server_sync=False,
        interest_radius=args.interest_radius,
        # store_true flags pass None when absent so NF_SERVE_BATCH /
        # NF_SERVE_OVERLAP env knobs still decide (A/B harness parity)
        serve_batch=args.serve_batch or None,
        serve_overlap=args.serve_overlap or None,
    )
    sent = {"msgs": 0, "bytes": 0}

    def fake_send(conn_id: int, msg_id: int, body: bytes) -> bool:
        sent["msgs"] += 1
        sent["bytes"] += len(body)
        return True

    role.server.send_raw = fake_send
    # S simulated sessions with live Player avatars in the NPC scene
    n_sessions = args.sessions
    for i in range(n_sessions):
        ident = Ident(svrid=99, index=i + 1)
        sess = Session(ident=ident, conn_id=1000 + (i % 8), account=f"bot{i}")
        g = role.kernel.create_object("Player", {"Name": f"Bot{i}"},
                                      scene=1, group=0)
        sess.guid = g
        role.sessions[ident_key(ident)] = sess
        role._guid_session[g] = ident_key(ident)

    dt = world.config.dt * 1.0001  # epsilon: defeat float >= dt jitter
    now = 1000.0
    # warm up: compile + first flush
    for _ in range(3):
        now += dt
        role.execute(now)
    jax.block_until_ready(role.kernel.state.classes["NPC"].i32)
    sent["msgs"] = sent["bytes"] = 0
    frame_ms = []
    t_all = time.perf_counter()
    for _ in range(args.ticks):
        now += dt
        t0 = time.perf_counter()
        role.execute(now)
        jax.block_until_ready(role.kernel.state.classes["NPC"].i32)
        frame_ms.append(1000 * (time.perf_counter() - t0))
    elapsed = time.perf_counter() - t_all
    # percentiles come from the role's telemetry registry — the same
    # histogram a /metrics scrape of this role would serve
    frame_hist = role.telemetry.registry.histogram(
        "nf_bench_frame_seconds", "served-path frame wall time",
        window=max(512, args.ticks),
    )
    for ms in frame_ms:
        frame_hist.observe(ms / 1e3)
    p50, p95, p99 = _hist_pcts(frame_hist)

    rate = n * args.ticks / elapsed
    dev = __import__("jax").devices()[0]
    return {
        "metric": "served_entity_ticks_per_sec_per_chip",
        "value": round(rate, 1),
        "unit": "entity-ticks/s",
        "vs_baseline": round(rate / NORTH_STAR_RATE, 4),
        "detail": {
            "entities": n,
            "ticks": args.ticks,
            "seed": args.seed,
            "sessions": n_sessions,
            "elapsed_s": round(elapsed, 4),
            "frame_ms_p50": p50,
            "frame_ms_p95": p95,
            "frame_ms_p99": p99,
            "sync_msgs": sent["msgs"],
            "sync_bytes": sent["bytes"],
            "interest_radius": args.interest_radius,
            "serve_batch": bool(role.serve_batch),
            "serve_overlap": bool(role.serve_overlap),
            "device": str(dev),
            "platform": dev.platform,
            "binning": binning_mode(),
            # per-stage frame waterfall (ISSUE 7): p50/p95/mean ms per
            # pipeline stage from the role's StageClock, plus the last
            # frame's exact breakdown and trace-sidecar counters
            "pipeline": role.pipeline_stats(),
            # compiled-cost evidence + the measured roofline: per-stage
            # achieved-vs-peak fractions from CostBook x StageClock
            "costbook": _costbook_detail(role.kernel.costbook,
                                         role.pipeline_stats()),
        },
    }


def run_sharded(args) -> dict:
    """BASELINE config-5 evidence: the SAME world and tick, sharded over
    an n-device mesh (virtual CPU devices stand in for a pod slice —
    the driver's dryrun validates compilation, this measures a full
    fused run and reports mesh geometry + throughput)."""
    from noahgameframe_tpu.utils.platform import force_cpu, init_compile_cache

    jax = force_cpu(args.sharded)
    init_compile_cache()  # $NF_COMPILE_CACHE: pay the XLA compile once

    from noahgameframe_tpu.game import build_benchmark_world
    from noahgameframe_tpu.ops.stencil import binning_mode
    from noahgameframe_tpu.parallel import ShardedKernel

    n = args.entities
    world = build_benchmark_world(n, combat=not args.no_combat,
                                  seed=args.seed)
    sk = ShardedKernel(world.kernel, n_devices=args.sharded)
    sk.place()
    k = world.kernel
    # the benchmark loop reuses ONE compiled sharded step (host-looped,
    # state device-resident) — compile cost is a single step's, not the
    # round-3 fori-fused 319 s program
    t_c0 = time.perf_counter()
    sk.run_device(1, fused=False)  # compile + first tick
    jax.block_until_ready(k.state.classes["NPC"].i32)
    compile_s = time.perf_counter() - t_c0
    t0 = time.perf_counter()
    sk.run_device(args.ticks, fused=False)
    jax.block_until_ready(k.state.classes["NPC"].i32)
    dt = time.perf_counter() - t0
    rate = n * args.ticks / dt
    grid_drop, att_drop = _overflow_gauges(world)
    return {
        "metric": "sharded_entity_ticks_per_sec",
        "value": round(rate, 1),
        "unit": "entity-ticks/s",
        "vs_baseline": round(rate / NORTH_STAR_RATE, 4),
        "detail": {
            "entities": n,
            "ticks": args.ticks,
            "seed": args.seed,
            "devices": args.sharded,
            "mesh": str(dict(sk.mesh.shape)),
            "elapsed_s": round(dt, 4),
            "compile_plus_first_tick_s": round(compile_s, 2),
            "tick_ms": round(1000 * dt / args.ticks, 3),
            "platform": jax.devices()[0].platform,
            "per_device_rate": round(rate / args.sharded, 1),
            "combat": not args.no_combat,
            "grid_overflow_max": grid_drop,
            "att_overflow_max": att_drop,
            "binning": binning_mode(),
            "costbook": _costbook_detail(k.costbook),
        },
    }


def run_mesh_migrate(args) -> dict:
    """ISSUE 15 r09 evidence: the unified engine's full-row migration
    ladder.  Sweeps entity count x mesh width x migration budget through
    the ONE engine (SpatialWorld as a thin preset over Kernel +
    ShardedKernel + RowMigrationModule) on virtual CPU devices —
    config-5 shape.  Each point reports throughput, migration traffic
    (rows and analytic collective bytes = row_bytes x migrated), and a
    CostBook recompile gate: after the 2-tick warmup, the sweep loop
    must compile NOTHING new (`unexplained_recompiles == 0`)."""
    from noahgameframe_tpu.utils.platform import force_cpu, init_compile_cache

    jax = force_cpu(args.mesh_migrate)
    init_compile_cache()

    import numpy as np

    from noahgameframe_tpu.ops.stencil import auto_bucket, binning_mode
    from noahgameframe_tpu.parallel.spatial import SpatialGeom, SpatialWorld

    entities = [int(x) for x in
                (args.mig_entities or "100000,1000000").split(",")]
    if args.mig_widths:
        widths = [int(x) for x in args.mig_widths.split(",")]
    else:
        widths = [w for w in (2, 4, 8) if w <= args.mesh_migrate] or [1]
    budgets = [int(x) for x in (args.mig_budgets or "2048,8192").split(",")]
    ticks = args.mig_ticks

    def point(n, shards, budget):
        radius = 4.0
        cell = 4.0
        extent = max(64.0, float(np.sqrt(n / 0.4)))
        width = max(shards, int(extent / cell))
        width -= width % shards
        extent = width * cell
        bucket = auto_bucket(n, width) + 8
        att_bucket = auto_bucket(max(1, n // 30), width, lo=4, align=2) + 4
        geom = SpatialGeom(
            extent=extent, cell_size=cell, width=width, n_shards=shards,
            bucket=bucket, att_bucket=att_bucket, radius=radius,
            mig_budget=budget, speed=1.0, attack_period=30,
        )
        rng = np.random.default_rng(args.seed)
        pos = rng.uniform(1.0, extent - 1.0, (n, 2)).astype(np.float32)
        hp = np.full(n, 10_000, np.int32)
        atk = rng.integers(5, 20, n).astype(np.int32)
        camp = (np.arange(n) % 2).astype(np.int32)
        world = SpatialWorld(geom)
        world.place(pos, hp, atk, camp)
        t_c0 = time.perf_counter()
        world.step(2)  # compile + warm (stats fetch path included)
        compile_s = time.perf_counter() - t_c0
        mark = world.costbook.mark()
        migrated = overflow = dropped = 0
        t0 = time.perf_counter()
        for _ in range(ticks):
            world.step(1)
            s = world.stats_last.sum(axis=0)
            migrated += int(s[0])
            overflow += int(s[1])
            dropped += int(s[2])
        dt = time.perf_counter() - t0
        unexplained = world.costbook.unexplained_since(mark)
        row_b = world._mig.row_bytes() if world._mig is not None else 0
        return {
            "entities": n,
            "devices": shards,
            "mesh": str({"shard": shards}),
            "mig_budget": budget,
            "ticks": ticks,
            "compile_plus_warm_s": round(compile_s, 2),
            "tick_ms": round(1000 * dt / ticks, 3),
            "entity_ticks_per_sec": round(n * ticks / dt, 1),
            "migrated_total": migrated,
            "mig_overflow_total": overflow,
            "mig_dropped_total": dropped,
            "row_bytes": row_b,
            # analytic wire cost of the migration collective: every
            # migrated row moves its FULL ClassState (banks + records +
            # timers + alive) once
            "migrate_collective_bytes_per_tick": (
                row_b * migrated // max(1, ticks)
            ),
            "unexplained_recompiles": len(unexplained),
            "geometry": {"width": width, "slab_h": geom.slab_h,
                         "bucket": bucket, "att_bucket": att_bucket},
            "costbook": _costbook_detail(world.costbook),
        }

    points = []
    for n in entities:
        for shards in widths:
            for budget in budgets:
                # full product at the smallest N ranks the knobs; larger
                # Ns run the headline config only (CPU wall-clock bound)
                if n != entities[0] and (shards != widths[-1]
                                         or budget != budgets[-1]):
                    continue
                points.append(point(n, shards, budget))
    best = max(points, key=lambda p: p["entity_ticks_per_sec"])
    return {
        "metric": "mesh_migrate_entity_ticks_per_sec",
        "value": best["entity_ticks_per_sec"],
        "unit": "entity-ticks/s",
        "vs_baseline": round(best["entity_ticks_per_sec"] / NORTH_STAR_RATE,
                             4),
        "detail": {
            "devices": args.mesh_migrate,
            "seed": args.seed,
            "platform": jax.devices()[0].platform,
            "binning": binning_mode(),
            "engine": "unified (full-row ClassState migration)",
            "unexplained_recompiles": sum(p["unexplained_recompiles"]
                                          for p in points),
            "points": points,
        },
    }


def run_reshard(args) -> dict:
    """ISSUE 17 r10 evidence: the elastic reshard ladder.  Each point
    builds a lean migrating world on a 2-device mesh, grows it to 4 and
    drains back to 3 under continuous motion churn, and reports the
    reshard costs the live serving path pays: rebalance/exodus ticks,
    wall time per op (retrace included), rows moved, analytic collective
    bytes (full ClassState row x rows moved), and the same CostBook gate
    as the migration ladder — after the warmup mark, every recompile
    must be generation-sanctioned (``unexplained_recompiles == 0``)."""
    from noahgameframe_tpu.utils.platform import force_cpu

    # NO persistent compile cache here, deliberately: jaxlib 0.4.37's
    # CPU client segfaults (heap corruption) deserializing a CACHE HIT
    # of the exodus-armed drain executable — cold compiles run fine,
    # the second process to hit the entry dies at dispatch.  The
    # ladder's compiles are single-step and cheap, so skipping
    # init_compile_cache() costs seconds and removes the landmine.
    if args.platform == "tpu":
        # chip-native: the ladder runs over the first 4 real devices
        # (grow targets a 4-wide mesh); the harvest queue guards on the
        # backend actually exposing them
        import jax

        if len(jax.devices()) < 4:
            raise RuntimeError(
                f"--reshard --platform tpu needs >=4 devices, backend "
                f"exposes {len(jax.devices())}")
    else:
        jax = force_cpu(args.reshard)

    import jax.numpy as jnp
    import numpy as np

    from noahgameframe_tpu.core.schema import ClassDef, ClassRegistry, prop, record
    from noahgameframe_tpu.core.store import StoreConfig, with_class
    from noahgameframe_tpu.kernel.kernel import Kernel
    from noahgameframe_tpu.kernel.module import Module
    from noahgameframe_tpu.parallel.elastic import ElasticMesh
    from noahgameframe_tpu.parallel.mesh import make_mesh
    from noahgameframe_tpu.parallel.rowmigrate import (
        RowMigrationModule,
        SpatialPlacement,
    )
    from noahgameframe_tpu.parallel.shard import ShardedKernel

    extent = 256.0

    class _Drift(Module):
        name = "drift"

        def __init__(self):
            super().__init__()
            self.add_phase("move", self._move, order=10)

        def _move(self, state, ctx):
            cs = state.classes["Npc"]
            y = jnp.mod(cs.vec[:, 0, 1] + 1.5, extent)
            return with_class(state, "Npc",
                              cs.replace(vec=cs.vec.at[:, 0, 1].set(y)))

    # capacities must split at every width visited (2, 4 and the
    # post-drain 3) — LCM 12
    caps = [int(x) for x in (args.mig_entities or "12000,60000").split(",")]
    budgets = [int(x) for x in (args.mig_budgets or "512,2048").split(",")]

    def point(cap, budget):
        if cap % 12:
            raise ValueError(f"--reshard capacities must divide by 12 "
                             f"(widths 2/4/3 are visited), got {cap}")
        reg = ClassRegistry()
        reg.define(ClassDef(name="Npc", properties=[
            prop("Id", "int"), prop("HP", "int"), prop("Position", "vector2"),
        ], records=[
            record("Bag", 3, [("item", "int"), ("weight", "float")]),
        ]))
        k = Kernel(reg, store_config=StoreConfig(
            default_capacity=cap, capacities={"Npc": cap},
            timer_slots={"Npc": 2},
        ), seed=args.seed)
        mesh = make_mesh(2)
        mig = RowMigrationModule(SpatialPlacement(
            class_name="Npc", pos_prop="Position", extent=extent,
            cell_size=8.0, width=32, n_shards=2, mig_budget=budget,
        ), mesh=mesh, order=20)
        k.build([_Drift(), mig])
        mig.bind(k)

        live = cap // 2
        rng = np.random.default_rng(args.seed)
        i32 = np.zeros((cap, 2), np.int32)
        i32[:, 0] = np.arange(cap)
        i32[:live, 1] = 100
        vec = np.zeros((cap, 1, 3), np.float32)
        vec[:live, 0, 0] = rng.uniform(1.0, extent - 1, live)
        vec[:live, 0, 1] = rng.uniform(1.0, extent - 1, live)
        alive = np.zeros(cap, bool)
        alive[:live] = True
        cs = k.state.classes["Npc"].replace(
            i32=jnp.asarray(i32), vec=jnp.asarray(vec),
            alive=jnp.asarray(alive))
        k.state = with_class(k.state, "Npc", cs)

        sk = ShardedKernel(k, mesh=mesh)
        sk.place()
        el = ElasticMesh(sk, migration=mig, ident_cols={"Npc": 0},
                         exodus_tick_bound=512)
        sk.run_device(2, fused=False)  # compile + warm at width 2
        mark = k.costbook.mark()

        def drive(begin):
            t0 = time.perf_counter()
            begin()
            for _ in range(600):
                el.poll()
                if el.inflight is None:
                    break
                sk.run_device(1, fused=False)
            assert el.inflight is None, "reshard op never settled"
            return time.perf_counter() - t0, el.ops_done[-1]

        grow_s, grow = drive(lambda: el.begin_grow(4))
        drain_s, drain = drive(lambda: el.begin_drain(1))
        unexplained = k.costbook.unexplained_since(mark)
        row_b = mig.row_bytes()
        moved = int(el.rows_moved_total)
        return {
            "capacity": cap,
            "live": live,
            "mig_budget": budget,
            "grow_wall_s": round(grow_s, 2),
            "grow_rebalance_ticks": int(grow["rebalance_ticks"]),
            "drain_wall_s": round(drain_s, 2),
            "drain_exodus_ticks": int(drain["exodus_ticks"]),
            "drained_in_budget": bool(drain["drained_in_budget"]),
            "pop_conserved": all(
                op["pop_after"] == op["pop_before"] == live
                for op in (grow, drain)),
            "rows_moved_total": moved,
            "dropped_rows": int(el.dropped_rows),
            "row_bytes": row_b,
            # analytic wire cost: every re-homed row ships its FULL
            # ClassState (banks + records + timers + alive) once
            "reshard_collective_bytes": row_b * moved,
            "unexplained_recompiles": len(unexplained),
            "costbook": _costbook_detail(k.costbook),
        }

    points = []
    for cap in caps:
        for budget in budgets:
            # full product at the smallest capacity ranks the budget
            # knob; larger rungs run the headline config only
            if cap != caps[0] and budget != budgets[-1]:
                continue
            points.append(point(cap, budget))
    head = points[-1]
    return {
        "metric": "reshard_drain_exodus_ticks",
        "value": head["drain_exodus_ticks"],
        "unit": "ticks",
        "detail": {
            "devices": args.reshard,
            "seed": args.seed,
            "platform": jax.devices()[0].platform,
            "widths_visited": [2, 4, 3],
            "all_gates": all(
                p["pop_conserved"] and p["dropped_rows"] == 0
                and p["unexplained_recompiles"] == 0 for p in points),
            "unexplained_recompiles": sum(p["unexplained_recompiles"]
                                          for p in points),
            "points": points,
        },
    }


def run_rooms(args) -> dict:
    """ISSUE 19 r12 evidence: the many-worlds rooms ladder.  Each rung
    admits R independent rooms into ONE vmapped RoomBatch sharded
    room-major over the mesh — one recipe world built per rung, packed
    once, admitted R times with per-room rng variation, so setup stays
    O(1) worlds.  Reported per rung: admit cost, per-batch-tick p50/p99
    (tick() with the per-room counter-bank fetch — the served-path
    honest frame), fused room-ticks/sec, then a re-home churn phase
    with a zero-dropped-rows account and the same CostBook
    zero-unexplained-recompile gate as the migration ladders."""
    from noahgameframe_tpu.utils.platform import force_cpu

    if args.platform == "tpu":
        import jax
    else:
        jax = force_cpu(args.rooms)

    import numpy as np

    from noahgameframe_tpu.game import GameWorld
    from noahgameframe_tpu.game.world import WorldConfig
    from noahgameframe_tpu.parallel.mesh import ROOMS_AXIS, make_mesh
    from noahgameframe_tpu.parallel.rooms import RoomBatch, RoomBinPacker

    counts = [int(x) for x in (args.rooms_count or "16,64,256").split(",")]
    per_room = int(args.rooms_entities)
    seeded = max(1, per_room // 2)
    ticks = int(args.rooms_ticks)
    train_k = int(getattr(args, "train", 0) or 0)
    mesh = make_mesh(args.rooms, axis=ROOMS_AXIS)

    def r12_point(n_rooms):
        """The committed r12 (K=1) rung matching this one, for honest
        speedup ratios in the train arm; None when no artifact."""
        name = ("r12_rooms_tpu.json" if args.platform == "tpu"
                else "r12_rooms_cpu.json")
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "bench_runs", name)
        try:
            with open(path) as f:
                for p in json.load(f)["detail"]["points"]:
                    if p.get("rooms") == n_rooms:
                        return p
        except Exception:  # noqa: BLE001
            return None
        return None

    def point(n_rooms):
        if n_rooms % args.rooms:
            raise ValueError(f"--rooms-count {n_rooms} not divisible by "
                             f"the {args.rooms}-device rooms mesh")
        t0 = time.perf_counter()
        w = GameWorld(WorldConfig(
            npc_capacity=per_room, player_capacity=8, extent=64.0,
            seed=args.seed, middleware=False, combat=True,
            movement=True, regen=True, verlet_skin=2.0))
        w.start()
        w.scene.create_scene(1, width=64.0)
        w.seed_npcs(seeded, rng=np.random.default_rng(args.seed + 100))
        w.kernel._ensure_aux()
        batch = RoomBatch(w.kernel, n_rooms, mesh=mesh)
        packer = RoomBinPacker(batch.capacity,
                               n_blocks=mesh.devices.size)
        build_s = time.perf_counter() - t0

        def room_of(i):
            return w.kernel.state.replace(
                rng=jax.random.PRNGKey(args.seed + i))

        # warm-up compiles every entry once (admit/step/run/extract,
        # plus the K-tick train when elected), then the no-recompile
        # gate arms: churn after the mark must be free (slot indices
        # are traced scalars)
        batch.admit(packer.alloc(), room_of(0))
        batch.tick()
        batch.run(1)
        if train_k > 1:
            batch.configure_train(train_k)
            batch.train(train_k)
        batch.extract(0)
        batch.rehome(0, 1)
        packer.free(0)
        mark = batch.costbook.mark()

        # fill every lane but one — the spare slot is what the churn
        # phase rotates rooms through
        t0 = time.perf_counter()
        used = []
        while packer.free_count > 1:
            slot = packer.alloc()
            batch.admit(slot, room_of(len(used)))
            used.append(slot)
        jax.block_until_ready(batch.state)
        admit_s = time.perf_counter() - t0

        # per-frame latency: tick() includes the [R,L] counter fetch
        lat = []
        for _ in range(ticks):
            t0 = time.perf_counter()
            counters = batch.tick()
            lat.append(time.perf_counter() - t0)
        lat_ms = np.sort(np.asarray(lat)) * 1e3
        p50 = float(lat_ms[len(lat_ms) // 2])
        p99 = float(lat_ms[min(len(lat_ms) - 1,
                               int(len(lat_ms) * 0.99))])

        # fused throughput: one dispatch, zero host syncs inside
        t0 = time.perf_counter()
        batch.run(2 * ticks)
        jax.block_until_ready(batch.state)
        run_s = time.perf_counter() - t0
        room_ticks = n_rooms * 2 * ticks / run_s

        # K-tick train throughput (ISSUE 20): same 2*ticks span as the
        # fused window, but every tick's [R, L] counter lane comes back
        # to the host — the OBSERVED path at ceil(n/K) dispatches.  The
        # dispatch gate pins the count exactly; a retrace or a silent
        # per-tick fallback would break it.
        train = {}
        if train_k > 1:
            n_train = 2 * ticks
            d0 = batch.train_dispatches
            t0 = time.perf_counter()
            lanes = batch.train(n_train)
            train_s = time.perf_counter() - t0
            t_dispatches = batch.train_dispatches - d0
            want = n_train // train_k  # tail singles ride _jit_step
            train = {
                "tick_train": train_k,
                "train_ticks_timed": n_train,
                "train_tick_ms": round(train_s * 1e3 / n_train, 3),
                "train_room_ticks_per_sec": round(
                    n_rooms * n_train / train_s, 1),
                "train_dispatches": t_dispatches,
                "train_dispatch_gate": t_dispatches == want,
                "train_rows": int(lanes.shape[0]),
                "train_fetch_bytes": batch.train_fetch_bytes,
            }
            # honest ratios against the committed K=1 round: both the
            # observed path it replaces (r12 tick_p50, per-tick fetch)
            # and the fused path it cannot beat on fetch volume
            base = r12_point(n_rooms)
            if base:
                b_ms = float(base["tick_p50_ms"])
                b_obs = n_rooms / b_ms * 1e3
                train["baseline_r12_k1_tick_ms"] = b_ms
                train["baseline_r12_k1_room_ticks_per_sec"] = round(
                    b_obs, 1)
                train["speedup_vs_r12_k1_observed"] = round(
                    train["train_room_ticks_per_sec"] / b_obs, 2)
                b_fused = float(base["room_ticks_per_sec"])
                train["baseline_r12_fused_room_ticks_per_sec"] = b_fused
                train["speedup_vs_r12_fused"] = round(
                    train["train_room_ticks_per_sec"] / b_fused, 2)

        # churn: rotate rooms through the spare slot, nothing may drop
        def rows():
            return int(np.asarray(
                batch.state.classes["NPC"].alive)[used].sum())

        before = rows()
        rng = np.random.default_rng(args.seed)
        for _ in range(int(args.rooms_churn)):
            src = used.pop(int(rng.integers(0, len(used))))
            dst = packer.alloc()
            batch.rehome(src, dst)
            packer.free(src)
            used.append(dst)
        dropped = before - rows()
        unexplained = batch.costbook.unexplained_since(mark)

        # digest parity (ISSUE 20 acceptance): fresh train batch vs a
        # fresh single-ticking control, 120 ticks — every tick's
        # state_digest lane bit-identical across all R rooms, ragged
        # tail included.  Runs after the gates: enable_digest() is a
        # sanctioned retrace and must not pollute the churn CostBook.
        parity = {}
        if train_k > 1:
            w.kernel.enable_digest()

            def parity_batch():
                pb = RoomBatch(w.kernel, n_rooms, mesh=mesh)
                pk = RoomBinPacker(pb.capacity,
                                   n_blocks=mesh.devices.size)
                for i in range(n_rooms):
                    pb.admit(pk.alloc(), room_of(i))
                return pb

            pb_t, pb_c = parity_batch(), parity_batch()
            pb_t.configure_train(train_k)
            p_ticks = 120
            lanes_p = pb_t.train(p_ticks)
            ok = True
            for i in range(p_ticks):
                c = pb_t.kernel.decode_counters(lanes_p[i])
                ctl = pb_c.tick()
                if not (np.array_equal(c["state_digest"],
                                       ctl["state_digest"])
                        and np.array_equal(c["tick"], ctl["tick"])):
                    ok = False
                    break
            parity = {"digest_parity_ticks": p_ticks,
                      "digest_parity": ok}

        return {
            "rooms": n_rooms,
            "rooms_admitted": len(used),
            "entities_per_room": seeded,
            "build_wall_s": round(build_s, 2),
            "admit_wall_s": round(admit_s, 2),
            "admit_ms_per_room": round(admit_s * 1e3 / n_rooms, 3),
            "tick_p50_ms": round(p50, 3),
            "tick_p99_ms": round(p99, 3),
            "room_ticks_per_sec": round(room_ticks, 1),
            "entity_ticks_per_sec": round(room_ticks * seeded, 1),
            "counters_sample": {k: int(np.asarray(v).sum())
                                for k, v in counters.items()},
            "rehomed": int(args.rooms_churn),
            "dropped_rows": int(dropped),
            "unexplained_recompiles": len(unexplained),
            **train,
            **parity,
            "costbook": _costbook_detail(batch.costbook),
        }

    points = [point(n) for n in counts]
    head = points[-1]
    return {
        "metric": ("rooms_train_room_ticks_per_sec" if train_k > 1
                   else "rooms_room_ticks_per_sec"),
        "value": (head["train_room_ticks_per_sec"] if train_k > 1
                  else head["room_ticks_per_sec"]),
        "unit": "room-ticks/s",
        "detail": {
            "devices": args.rooms,
            "seed": args.seed,
            "platform": jax.devices()[0].platform,
            "ticks_timed": int(args.rooms_ticks),
            "tick_train": train_k,
            "all_gates": all(
                p["dropped_rows"] == 0
                and p["unexplained_recompiles"] == 0
                and p.get("train_dispatch_gate", True)
                and p.get("digest_parity", True) for p in points),
            "points": points,
        },
    }


def run_bench(args) -> dict:
    import jax

    from noahgameframe_tpu.game import build_benchmark_world
    from noahgameframe_tpu.ops.stencil import binning_mode
    from noahgameframe_tpu.ops.verlet import skin_from_env
    from noahgameframe_tpu.utils.platform import init_compile_cache

    init_compile_cache()
    n = args.entities
    world = build_benchmark_world(n, combat=not args.no_combat,
                                  seed=args.seed)
    k = world.kernel

    train_k = int(getattr(args, "train", 0) or 0)
    if train_k > 1:
        # K-tick train arm (ISSUE 20): the OBSERVED tick path — every
        # per-tick lane (digests, diffs, deaths, events) fans out on the
        # host — in ceil(ticks/K) dispatches instead of one per tick.
        # tick_ms below is amortized PER TICK, so decide_tuning compares
        # it against the fused baseline directly: NF_TICK_TRAIN only
        # promotes when full observability beats the blind fused loop.
        t_c0 = time.perf_counter()
        k.configure_train(train_k)
        k.train(train_k)
        jax.block_until_ready(k.state.classes["NPC"].i32)
        compile_s = time.perf_counter() - t_c0

        d0 = k.train_dispatches
        t0 = time.perf_counter()
        k.train(args.ticks)
        jax.block_until_ready(k.state.classes["NPC"].i32)
        dt = time.perf_counter() - t0
        train_detail = {
            "tick_train": train_k,
            "train_dispatches": k.train_dispatches - d0,
            "train_ticks_timed": args.ticks,
            "train_fetch_bytes": k.train_fetch_bytes,
        }
        # the latency passes below ride run_device; warm its compile
        # outside their timed windows
        k.run_device(1, reconcile=False)
        jax.block_until_ready(k.state.classes["NPC"].i32)
    else:
        train_detail = {}
        # compile + warm up (the trip count is a traced scalar: this ONE
        # compile serves the timed loop, the single-step pass, and every
        # latency window below)
        t_c0 = time.perf_counter()
        k.run_device(args.ticks)
        jax.block_until_ready(k.state.classes["NPC"].i32)
        compile_s = time.perf_counter() - t_c0

        t0 = time.perf_counter()
        k.run_device(args.ticks)
        jax.block_until_ready(k.state.classes["NPC"].i32)
        dt = time.perf_counter() - t0

    # per-tick latency distribution on the single-step path (the latency a
    # 30 Hz world-tick loop would see; run_device amortises dispatch, the
    # single step does not).  Reuses run_device's one compiled program
    # with a trip count of 1 — the separately-compiled _trace_step
    # program was a SECOND multi-minute 1M XLA compile that timed out
    # whole bench runs over the round-5 tunnel.
    # percentile math + sample windows live in the telemetry registry:
    # bench JSON reads the SAME histograms a /metrics scrape would
    reg = world.telemetry.registry
    lat_hist = reg.histogram(
        "nf_bench_tick_seconds", "single-dispatch tick latency"
    )
    for _ in range(max(8, min(64, args.ticks))):
        t1 = time.perf_counter()
        k.run_device(1, reconcile=False)
        jax.block_until_ready(k.state.classes["NPC"].i32)
        lat_hist.observe(time.perf_counter() - t1)
    p50, p95, p99 = _hist_pcts(lat_hist)

    # DEVICE-honest latency: the single-step numbers above include one
    # dispatch + tunnel round trip PER TICK, which over the remote-TPU
    # link dwarfs the compute at small N (round-3 verdict: p50 191.8 ms
    # vs 120.6 ms fused mean at 1M — an artifact of the harness, not the
    # chip).  Here each sample is a fused window of `lat_k` ticks in ONE
    # dispatch (run_device), so per-tick RTT pollution is RTT/lat_k;
    # window count adapts to a fixed wall budget, floor 64, cap 256.
    tick_s_est = max(1e-5, dt / args.ticks)
    if args.lat_k:
        lat_k = max(1, args.lat_k)
    else:
        # auto: size the window so one dispatch RTT (~80 ms over the
        # tunnel) is ~5% of it — window wall ≈ 1.6 s.  Trip count is a
        # traced scalar in run_device, so any lat_k reuses the one
        # compiled program.
        lat_k = max(4, min(256, int(round(1.6 / tick_s_est))))
    # floor 24 (p95 stays meaningful, p99 ≈ max) — a 64-window floor at
    # auto lat_k would run ~5x over lat_budget_s at 1M on the tunnel
    n_windows = int(max(24, min(256, args.lat_budget_s / (lat_k * tick_s_est))))
    # reconcile=False: end-of-window death reconciliation is one
    # device→host fetch per class — over a remote-TPU tunnel that cost
    # ~1 s per window (r05 measured: 271 ms/tick apparent at 100k vs a
    # 26 ms fused mean), pure harness artifact.  One reconciling call
    # after the loop keeps host free-lists exact.
    k.run_device(lat_k, reconcile=False)  # warm the lat_k-sized compile
    jax.block_until_ready(k.state.classes["NPC"].i32)
    dev_hist = reg.histogram(
        "nf_bench_tick_seconds_device",
        "fused-window per-tick latency (RTT amortised over lat_k)",
    )
    for _ in range(n_windows):
        t1 = time.perf_counter()
        k.run_device(lat_k, reconcile=False)
        jax.block_until_ready(k.state.classes["NPC"].i32)
        dev_hist.observe((time.perf_counter() - t1) / lat_k)
    # Verlet cache effectiveness (NF_VERLET_SKIN > 0): lifetime counters
    # off the carried caches in state.aux — rebuilds/tick is the
    # amortization the skin bought (1.0 == rebuilt every tick).  Read
    # BEFORE the reconciling tick: if that tick observes bucket overflow
    # the combat module invalidates, which (correctly) drops the caches.
    verlet = {}
    for key, c in (getattr(k.state, "aux", None) or {}).items():
        if not key.startswith("verlet/"):
            continue
        reb = int(jax.device_get(c.rebuilds))
        reu = int(jax.device_get(c.reuses))
        verlet[key[len("verlet/"):]] = {
            "rebuilds": reb,
            "reuses": reu,
            "rebuilds_per_tick": round(reb / max(1, reb + reu), 4),
        }
    k.tick()  # reconcile host free-lists once, outside timing; also
    # fetches the on-device counter bank for the detail block below
    dp50, dp95, dp99 = _hist_pcts(dev_hist)
    grid_drop, att_drop = _overflow_gauges(world)
    # per-engine combat-fold cost attribution (combat.fold_p{0,1,2} in
    # detail.costbook.entries) — outside every timed region
    pallas_probe = _combat_cost_probe(world)

    ticks_per_s = args.ticks / dt
    rate = n * ticks_per_s
    dev = jax.devices()[0]
    return {
        "metric": "entities_ticked_per_sec_per_chip",
        "value": round(rate, 1),
        "unit": "entity-ticks/s",
        "vs_baseline": round(rate / NORTH_STAR_RATE, 4),
        "detail": {
            "entities": n,
            "ticks": args.ticks,
            "seed": args.seed,
            "elapsed_s": round(dt, 4),
            "compile_and_warmup_s": round(compile_s, 2),
            "ticks_per_s": round(ticks_per_s, 2),
            "tick_ms": round(1000 * dt / args.ticks, 3),
            "tick_ms_p50": p50,
            "tick_ms_p95": p95,
            "tick_ms_p99": p99,
            # windowed (RTT-discounted) distribution — the honest chip
            # numbers; p50 here should track tick_ms (the fused mean)
            "tick_ms_p50_device": dp50,
            "tick_ms_p95_device": dp95,
            "tick_ms_p99_device": dp99,
            "lat_windows": n_windows,
            "lat_k": lat_k,
            "device": str(dev),
            "platform": dev.platform,
            "combat": not args.no_combat,
            **train_detail,
            "grid_overflow_max": grid_drop,
            "att_overflow_max": att_drop,
            # on-device counter bank from the reconciling tick above
            "tick_counters": dict(k.last_counters),
            # elected skin, whether or not Verlet caches engaged — a run
            # is only reproducible with the same (seed, skin) pair
            "verlet_skin": skin_from_env(),
            # which slot-assignment engine built the cell tables — the
            # label the count-vs-sort A/B (and decide_tuning) reads
            "binning": binning_mode(),
            # which combat fold engine ran (0 split-XLA / 1 split-Pallas
            # / 2 fused table-free), after any VMEM downgrade — the
            # label the NF_PALLAS tri-state A/B joins on
            **({"pallas_engine": pallas_probe.get("engine"),
                "pallas_probe": pallas_probe} if pallas_probe else {}),
            **({"verlet": verlet} if verlet else {}),
            # compiled-cost evidence: compile wall, recompiles+causes,
            # HBM peak, per-entry FLOPs/bytes (telemetry/costbook.py)
            "costbook": _costbook_detail(k.costbook),
        },
    }


LADDER = (1_000_000, 500_000, 250_000, 100_000)


def _served_probe(extra_args=()) -> dict:
    """One served-path measurement (100k entities, 500 sessions) in a
    subprocess; non-fatal on failure."""
    cmd = [
        sys.executable, "-u", __file__,
        "--entities", "100000", "--ticks", "30",
        "--served", "--sessions", "500", "--platform", "tpu",
        *extra_args,
    ]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=1800.0)
    except subprocess.TimeoutExpired:
        return {"error": "served probe timeout"}
    for ln in reversed((r.stdout or "").strip().splitlines()):
        if ln.startswith("{"):
            try:
                p = json.loads(ln)
            except json.JSONDecodeError:
                break
            return {
                "value": p.get("value"),
                "unit": p.get("unit"),
                "error": p.get("error"),
                **{
                    k: p.get("detail", {}).get(k)
                    for k in ("entities", "sessions", "frame_ms_p50",
                              "frame_ms_p99", "sync_msgs", "sync_bytes",
                              "interest_radius")
                },
            }
    return {"error": f"served probe rc={r.returncode}"}


def _run_session_sweep(args) -> dict:
    """--sweep-sessions: one served measurement per session count (the
    ISSUE 13 serving-edge scaling curve), each point in a SUBPROCESS so
    an OOM or wall-clock blowout at the 100k rung can't burn the smaller
    points.  With --sweep-ab every count also runs the legacy per-session
    engine first — the before/after `detail.pipeline` waterfall pair the
    r08 artifact records."""
    counts = [int(x) for x in args.sweep_sessions.split(",") if x.strip()]
    radius = 8.0 if args.interest_radius is None else args.interest_radius

    def one(sessions: int, serve_batch: bool) -> dict:
        cmd = [
            sys.executable, "-u", __file__,
            "--served", "--platform", "cpu",
            "--entities", str(args.entities), "--ticks", str(args.ticks),
            "--sessions", str(sessions), "--seed", str(args.seed),
            "--interest-radius", str(radius),
        ]
        if args.no_combat:
            cmd.append("--no-combat")
        if serve_batch:
            cmd.append("--serve-batch")
        if args.serve_overlap:
            cmd.append("--serve-overlap")
        point = {"sessions": sessions, "serve_batch": serve_batch}
        try:
            r = subprocess.run(
                cmd, capture_output=True, text=True,
                timeout=args.sweep_timeout,
            )
        except subprocess.TimeoutExpired:
            point["error"] = f"timeout after {args.sweep_timeout:.0f}s"
            return point
        for ln in reversed((r.stdout or "").strip().splitlines()):
            if ln.startswith("{"):
                try:
                    p = json.loads(ln)
                except json.JSONDecodeError:
                    break
                if p.get("error"):
                    point["error"] = p["error"]
                point["value"] = p.get("value")
                point["detail"] = p.get("detail")
                return point
        point["error"] = f"rc={r.returncode}"
        point["tail"] = (r.stderr or "").strip().splitlines()[-3:]
        return point

    points = []
    for s in counts:
        if args.sweep_ab:
            points.append(one(s, False))
        points.append(one(s, True))
    head = next(
        (p for p in points
         if p.get("serve_batch") and p.get("value") and not p.get("error")),
        None,
    )
    return {
        "metric": "served_session_sweep",
        "value": head["value"] if head else 0.0,
        "unit": "entity-ticks/s",
        "vs_baseline": round(
            (head["value"] / NORTH_STAR_RATE) if head else 0.0, 4
        ),
        "detail": {
            "entities": args.entities,
            "ticks": args.ticks,
            "seed": args.seed,
            "interest_radius": radius,
            "sweep_sessions": counts,
            "sweep_ab": bool(args.sweep_ab),
            "baseline_artifact": "r05_served_100k_2000s_cpu.json",
            "baseline_frame_ms_p99": 726.402,
            "points": points,
        },
    }


def _run_pallas_ab(args) -> dict:
    """--sweep-ab without --sweep-sessions: waterfall the three combat
    engines (NF_PALLAS 0 split-XLA / 1 split-Pallas fold / 2 fused
    table-free) in one invocation.  Each engine runs in a SUBPROCESS
    with an explicit ``--pallas`` pin — the knob is read at trace time,
    so respawning is the only way to get three honest traces — and a
    crash or OOM in one engine can't burn the others' points.  Each
    point keeps its ``combat.fold_p*`` costbook entry, so the r11
    artifact reads split-vs-fused bytes_accessed from one payload.
    With ``--train K`` a fourth arm rides along: the winning fused
    engine re-run under K-tick observed trains (r13)."""
    def one(engine: int, train: int = 0) -> dict:
        cmd = [
            sys.executable, "-u", __file__,
            "--entities", str(args.entities), "--ticks", str(args.ticks),
            "--seed", str(args.seed), "--platform", args.platform,
            "--pallas", str(engine),
        ]
        if train > 1:
            cmd += ["--train", str(train)]
        if args.no_combat:
            cmd.append("--no-combat")
        point = {"pallas": engine, "tick_train": train}
        try:
            r = subprocess.run(
                cmd, capture_output=True, text=True,
                timeout=args.sweep_timeout,
            )
        except subprocess.TimeoutExpired:
            point["error"] = f"timeout after {args.sweep_timeout:.0f}s"
            return point
        for ln in reversed((r.stdout or "").strip().splitlines()):
            if ln.startswith("{"):
                try:
                    p = json.loads(ln)
                except json.JSONDecodeError:
                    break
                if p.get("error"):
                    point["error"] = p["error"]
                point["value"] = p.get("value")
                d = p.get("detail") or {}
                for key in ("tick_ms", "tick_ms_p50_device", "platform",
                            "pallas_engine", "pallas_probe", "binning",
                            "tick_train", "train_dispatches"):
                    point[key] = d.get(key)
                entries = ((d.get("costbook") or {}).get("entries")) or {}
                point["fold_entries"] = {
                    name: e for name, e in entries.items()
                    if name.startswith("combat.fold_")
                }
                return point
        point["error"] = f"rc={r.returncode}"
        point["tail"] = (r.stderr or "").strip().splitlines()[-3:]
        return point

    points = [one(e) for e in (0, 1, 2)]
    train_k = int(getattr(args, "train", 0) or 0)
    if train_k > 1:
        points.append(one(2, train=train_k))
    head = next(
        (p for p in points if p.get("value") and not p.get("error")), None
    )
    return {
        "metric": "pallas_engine_ab",
        "value": head["value"] if head else 0.0,
        "unit": "entity-ticks/s",
        "vs_baseline": round(
            (head["value"] / NORTH_STAR_RATE) if head else 0.0, 4
        ),
        "detail": {
            "entities": args.entities,
            "ticks": args.ticks,
            "seed": args.seed,
            "platform": args.platform,
            "points": points,
        },
    }


def _run_ladder(probe_note, serve_args) -> None:
    """Driver-default path: try the flagship 1M config, halving on failure
    (round-2: a TPU worker crash at 1M burned the round's artifact).  Each
    rung runs in a SUBPROCESS so a crashed/poisoned TPU client can't take
    the parent — the parent always emits one JSON line."""
    attempts = []
    last_error = None
    for n in LADDER:
        cmd = [
            sys.executable, "-u", __file__,
            "--entities", str(n), "--ticks", "90", "--platform", "tpu",
        ] + serve_args
        try:
            r = subprocess.run(
                cmd, capture_output=True, text=True, timeout=2400.0
            )
        except subprocess.TimeoutExpired:
            # a rung that TIMES OUT (vs crashes) means the tunnel died
            # mid-run — smaller rungs would hang for 2400 s each too, so
            # stop laddering and let the caller fall back to CPU
            attempts.append({"entities": n, "outcome": "timeout"})
            last_error = f"rung {n}: timeout (tunnel died mid-run)"
            break
        line = None
        for ln in reversed((r.stdout or "").strip().splitlines()):
            if ln.startswith("{"):
                line = ln
                break
        if line is None:
            tail = (r.stderr or "").strip().splitlines()[-3:]
            attempts.append(
                {"entities": n, "outcome": f"rc={r.returncode}", "tail": tail}
            )
            last_error = f"rung {n}: no output (rc={r.returncode})"
            continue
        try:
            payload = json.loads(line)
        except json.JSONDecodeError:
            # a crash mid-print can leave a truncated '{' line — treat it
            # like a failed rung, never kill the parent emitter
            attempts.append({"entities": n, "outcome": "bad json"})
            last_error = f"rung {n}: unparseable output"
            continue
        if "error" in payload:
            attempts.append(
                {"entities": n, "outcome": "error", "error": payload["error"]}
            )
            last_error = payload["error"]
            continue
        if attempts:
            payload.setdefault("detail", {})["ladder_fallbacks"] = attempts
        if probe_note:
            payload["detail"]["accelerator_probe_note"] = probe_note
        if "--served" not in serve_args:
            # capture the SERVED path too (tick + diff flush + fan-out to
            # 500 sessions at 100k) so the round's artifact carries both
            # numbers (round-2 weak #6) — same combat config as the rung.
            # Both fan-out modes ride along: group broadcast (reference
            # parity) and the per-session interest stream (round-3 item 3)
            extra = [a for a in serve_args if a == "--no-combat"]
            if "--seed" in serve_args:
                i = serve_args.index("--seed")
                extra += serve_args[i:i + 2]
            payload.setdefault("detail", {})["served"] = _served_probe(extra)
            payload["detail"]["served_interest"] = _served_probe(
                extra + ["--interest-radius", "8.0"]
            )
        _emit(payload)
        return
    _emit(
        {
            "metric": "entities_ticked_per_sec_per_chip",
            "value": 0.0,
            "unit": "entity-ticks/s",
            "vs_baseline": 0.0,
            "error": last_error or "every ladder rung failed",
            "detail": {"ladder_fallbacks": attempts, "probe": probe_note},
        }
    )


def main() -> None:
    # persistent XLA compile cache by default: the in-round harvest
    # captures warm it, so the driver's end-of-round run of the same
    # shapes skips the multi-minute 1M compile (explicit env overrides;
    # set NF_COMPILE_CACHE= empty to disable)
    os.environ.setdefault("NF_COMPILE_CACHE", "/tmp/nf_xla_cache")
    ap = argparse.ArgumentParser()
    # entities/ticks default to None so a CPU fallback can tell "driver
    # default" apart from a user-pinned size (argparse prefix matching
    # makes sys.argv scans unreliable)
    ap.add_argument("--entities", type=int, default=None)
    ap.add_argument("--ticks", type=int, default=None)
    ap.add_argument("--no-combat", action="store_true")
    ap.add_argument(
        "--seed", type=int, default=42,
        help="world seed for the benchmark population; recorded in the "
             "BENCH json so any run can be reproduced (or replayed) "
             "exactly",
    )
    ap.add_argument(
        "--served", action="store_true",
        help="measure the served path (tick + diff flush + fan-out) "
             "instead of the fused device loop",
    )
    ap.add_argument("--sessions", type=int, default=50)
    ap.add_argument(
        "--interest-radius", type=float, default=None,
        help="served mode: per-session interest-filtered Position "
             "streams (quantized) instead of group-wide broadcast",
    )
    ap.add_argument(
        "--serve-batch", action="store_true",
        help="served mode: the NF_SERVE_BATCH engine (vmap-over-sessions "
             "interest deltas + batched host assembly) instead of the "
             "legacy per-session loops",
    )
    ap.add_argument(
        "--serve-overlap", action="store_true",
        help="served mode: double-buffered snapshots — frame N's serve "
             "overlaps frame N+1's device tick (implies --serve-batch; "
             "bounded <=1-tick staleness)",
    )
    ap.add_argument(
        "--sweep-sessions", default=None, metavar="N,N,...",
        help="served mode: run one measurement per session count "
             "(e.g. 2000,20000,100000), each in a subprocess, and emit "
             "one combined payload with per-point detail.pipeline "
             "waterfalls",
    )
    ap.add_argument(
        "--sweep-ab", action="store_true",
        help="with --sweep-sessions: also run the legacy engine at "
             "every count (before/after waterfall pairs).  Without "
             "--sweep-sessions: waterfall the three combat engines "
             "(--pallas 0/1/2), each in a subprocess, into one payload",
    )
    ap.add_argument(
        "--pallas", type=int, choices=(0, 1, 2), default=None,
        help="combat fold engine: 0 split-table XLA stencil, 1 "
             "split-table Pallas fold, 2 fused table-free neighborhood "
             "engine (VMEM-oversize configs downgrade to 0).  Sets "
             "NF_PALLAS for this process — the knob is read at trace "
             "time, so A/B sweeps respawn one subprocess per engine; "
             "overrides bench_runs/tuning.json",
    )
    ap.add_argument(
        "--sweep-timeout", type=float, default=900.0,
        help="per-point subprocess timeout for --sweep-sessions",
    )
    ap.add_argument(
        "--lat-k", type=int, default=0,
        help="ticks per fused window in the device-honest latency "
             "sampler (per-tick RTT pollution = one dispatch / lat-k); "
             "0 = auto-size for ~1.6 s windows",
    )
    ap.add_argument(
        "--lat-budget-s", type=float, default=20.0,
        help="wall budget for the windowed latency pass; window count "
             "adapts to it (floor 64, cap 256)",
    )
    ap.add_argument(
        "--sharded", type=int, default=0, metavar="N",
        help="run the mesh-sharded tick over N virtual CPU devices "
             "(BASELINE config-5 evidence) instead of the single-chip loop",
    )
    ap.add_argument(
        "--mesh-migrate", type=int, default=0, metavar="N",
        help="unified-engine migration ladder over N virtual CPU "
             "devices: entity count x mesh width x migration budget "
             "through the full-row ClassState migration, with a "
             "CostBook zero-unexplained-recompile gate (r09 evidence)",
    )
    ap.add_argument(
        "--reshard", type=int, default=0, metavar="N",
        help="elastic reshard ladder over N virtual CPU devices (needs "
             ">=4; with --platform tpu, over the first 4 real chips): "
             "grow 2->4 then drain->3 under motion churn, reporting "
             "rebalance/exodus ticks, reshard collective bytes and the "
             "zero-unexplained-recompile gate (r10 evidence); capacity/"
             "budget knobs reuse --mig-entities/--mig-budgets",
    )
    ap.add_argument(
        "--rooms", type=int, default=0, metavar="N",
        help="many-worlds rooms ladder over an N-device room-major "
             "mesh (virtual CPU devices, or the real chips with "
             "--platform tpu): R independent rooms vmapped as one "
             "batch, per-batch-tick p50/p99, fused room-ticks/sec, and "
             "a re-home churn phase gated on zero dropped rows + zero "
             "unexplained recompiles (r12 evidence)",
    )
    ap.add_argument(
        "--rooms-count", default=None, metavar="R,R,...",
        help="rooms ladder rungs (default 16,64,256; each must divide "
             "by --rooms)",
    )
    ap.add_argument(
        "--rooms-entities", type=int, default=64,
        help="per-room NPC capacity (half of it seeded live)",
    )
    ap.add_argument(
        "--rooms-churn", type=int, default=8,
        help="re-homes rotated through the spare slot per rung",
    )
    ap.add_argument(
        "--rooms-ticks", type=int, default=30,
        help="individually-timed batch ticks per rung (the fused "
             "throughput window runs 2x this)",
    )
    ap.add_argument(
        "--train", type=int, default=0, metavar="K",
        help="K-tick observed trains (NF_TICK_TRAIN): one lax.scan "
             "dispatch covers K ticks with every per-tick lane stacked "
             "[K,...] for the host.  Device-loop mode times k.train() "
             "instead of run_device(); the rooms ladder adds a train "
             "throughput arm + a 120-tick per-tick digest-parity gate "
             "against a K=1 control (r13 evidence).  0/1 = off",
    )
    ap.add_argument(
        "--mig-entities", default=None, metavar="N,N,...",
        help="mesh-migrate entity ladder (default 100000,1000000; the "
             "full knob product runs at the smallest count only)",
    )
    ap.add_argument(
        "--mig-widths", default=None, metavar="S,S,...",
        help="mesh-migrate mesh widths in shards (default 2,4,8 "
             "clipped to --mesh-migrate)",
    )
    ap.add_argument(
        "--mig-budgets", default=None, metavar="B,B,...",
        help="mesh-migrate per-direction row budgets (default 2048,8192)",
    )
    ap.add_argument(
        "--mig-ticks", type=int, default=10,
        help="timed ticks per mesh-migrate point (after a 2-tick warmup)",
    )
    ap.add_argument(
        "--platform",
        choices=("auto", "tpu", "cpu"),
        default="auto",
        help="auto: probe the accelerator, fall back to CPU on failure",
    )
    ap.add_argument(
        "--probe-timeout", type=float, default=90.0,
        help="accelerator probe subprocess timeout; a healthy backend "
             "answers in seconds, and the r05 240 s default just spent "
             "4 minutes confirming a hang (the probe retries once at "
             "min(60s, this) either way)",
    )
    args = ap.parse_args()
    pinned = args.entities is not None or args.ticks is not None
    if args.pallas is not None:
        # trace-time knob: must sit in the environment before the first
        # world build; an explicit flag beats tuning.json (which applies
        # via setdefault) and the inherited environment alike
        os.environ["NF_PALLAS"] = str(args.pallas)

    if args.sweep_ab and not args.sweep_sessions and not args.served:
        # the engine-waterfall parent never touches jax — every engine
        # point is a subprocess (NF_PALLAS is a trace-time knob: only a
        # respawn gives each engine an honest fresh trace)
        if args.entities is None:
            args.entities = 20_000  # the r11 acceptance geometry
        if args.ticks is None:
            args.ticks = 30
        _emit(_run_pallas_ab(args))
        return

    if args.served and args.sweep_sessions:
        # the sweep parent never touches jax — every point is a CPU
        # subprocess, so no platform probe / tuning applies here
        if args.entities is None:
            args.entities = 100_000
        if args.ticks is None:
            args.ticks = 8
        _emit(_run_session_sweep(args))
        return

    if args.reshard:
        if args.platform != "tpu" and args.reshard < 4:
            _emit(
                {
                    "metric": "reshard_drain_exodus_ticks",
                    "value": 0,
                    "unit": "ticks",
                    "error": "--reshard runs on N>=4 virtual CPU devices "
                             "or real chips via --platform tpu (the "
                             "ladder grows to a 4-wide mesh)",
                }
            )
            return
        try:
            _emit(run_reshard(args))
        except Exception as e:  # noqa: BLE001
            import traceback

            _emit(
                {
                    "metric": "reshard_drain_exodus_ticks",
                    "value": 0,
                    "unit": "ticks",
                    "error": f"{type(e).__name__}: {e}",
                    "detail": {
                        "trace_tail": traceback.format_exc().strip()
                        .splitlines()[-4:],
                    },
                }
            )
        return

    if args.rooms:
        try:
            _emit(run_rooms(args))
        except Exception as e:  # noqa: BLE001
            import traceback

            _emit(
                {
                    "metric": "rooms_room_ticks_per_sec",
                    "value": 0.0,
                    "unit": "room-ticks/s",
                    "error": f"{type(e).__name__}: {e}",
                    "detail": {
                        "trace_tail": traceback.format_exc().strip()
                        .splitlines()[-4:],
                    },
                }
            )
        return

    if args.mesh_migrate:
        try:
            _emit(run_mesh_migrate(args))
        except Exception as e:  # noqa: BLE001
            import traceback

            _emit(
                {
                    "metric": "mesh_migrate_entity_ticks_per_sec",
                    "value": 0.0,
                    "unit": "entity-ticks/s",
                    "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}",
                    "detail": {
                        "trace_tail": traceback.format_exc().strip()
                        .splitlines()[-4:],
                    },
                }
            )
        return

    probe_note = None
    if args.sharded:
        if args.served:
            _emit(
                {
                    "metric": "sharded_entity_ticks_per_sec",
                    "value": 0.0,
                    "unit": "entity-ticks/s",
                    "vs_baseline": 0.0,
                    "error": "--sharded measures the fused device loop; "
                             "combine with --served is not supported",
                }
            )
            return
        if args.platform == "tpu":
            _emit(
                {
                    "metric": "sharded_entity_ticks_per_sec",
                    "value": 0.0,
                    "unit": "entity-ticks/s",
                    "vs_baseline": 0.0,
                    "error": "--sharded runs on N virtual CPU devices; "
                             "it cannot be combined with --platform tpu "
                             "(one real chip has no mesh to shard over)",
                }
            )
            return
        if args.entities is None:
            args.entities = 512_000
        if args.ticks is None:
            args.ticks = 30
        try:
            _emit(run_sharded(args))
        except Exception as e:  # noqa: BLE001
            _emit(
                {
                    "metric": "sharded_entity_ticks_per_sec",
                    "value": 0.0,
                    "unit": "entity-ticks/s",
                    "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}",
                }
            )
        return
    if args.platform == "cpu":
        _force_cpu()
    elif args.platform == "auto":
        _request_tpu_yield()
        ok, note = _probe_accelerator(args.probe_timeout)
        if not ok:
            # one retry regardless of failure mode: r05's 240 s
            # backend-init hang was transient (the harvester's capture
            # was tearing down PJRT when the probe fired) and a second,
            # shorter attempt after the cooperative yield would have
            # saved the round's artifact from the CPU fallback
            ok, note = _probe_accelerator(min(60.0, args.probe_timeout))
        if not ok:
            probe_note = note
            _force_cpu()
            if not pinned:
                # CPU can't push the 1M config through the timed region
                # in reasonable wall-clock
                args.entities, args.ticks = 100_000, 30
        elif not pinned:
            serve = ["--served", "--sessions", str(args.sessions)] if args.served else []
            if args.no_combat:
                serve.append("--no-combat")
            serve += ["--seed", str(args.seed)]
            if args.pallas is not None:
                serve += ["--pallas", str(args.pallas)]
            _run_ladder(note, serve)
            return
    # platform == "tpu": let the default (axon) backend initialise in-process
    if args.entities is None:
        args.entities = 1_000_000
    if args.ticks is None:
        args.ticks = 90

    # apply measured A/B winners (harvest queue -> scripts/decide_tuning.py
    # -> bench_runs/tuning.json) on any on-chip path: --platform tpu, and
    # pinned --platform auto runs whose probe SUCCEEDED (probe_note is
    # only None here when the accelerator answered — unpinned successes
    # returned via the ladder above, whose tpu subprocesses re-enter this
    # branch themselves).  Explicit env vars still override via
    # setdefault.  CPU fallbacks keep defaults — the tuning was measured
    # on chip and does not transfer.
    tuning_applied = {}
    if args.platform == "tpu" or (
        args.platform == "auto" and probe_note is None
    ):
        tpath = os.path.join(os.path.dirname(__file__), "bench_runs",
                             "tuning.json")
        try:
            with open(tpath) as f:
                for k, v in (json.load(f).get("env") or {}).items():
                    if os.environ.setdefault(k, str(v)) == str(v):
                        tuning_applied[k] = str(v)
        except (OSError, json.JSONDecodeError, AttributeError):
            pass

    try:
        payload = run_served(args) if args.served else run_bench(args)
        if probe_note:
            payload["detail"]["accelerator_probe_error"] = probe_note
            payload["detail"]["platform_fallback"] = "cpu"
            best = _best_onchip_capture()
            if best:
                payload["detail"]["best_onchip_capture"] = best
        if tuning_applied:
            payload.setdefault("detail", {})["tuning_applied"] = tuning_applied
        _emit(payload)
    except Exception as e:  # noqa: BLE001
        import traceback

        _emit(
            {
                "metric": "entities_ticked_per_sec_per_chip",
                "value": 0.0,
                "unit": "entity-ticks/s",
                "vs_baseline": 0.0,
                "error": f"{type(e).__name__}: {e}",
                "detail": {
                    "entities": args.entities,
                    "ticks": args.ticks,
                    "probe": probe_note,
                    "trace_tail": traceback.format_exc().strip().splitlines()[-4:],
                },
            }
        )
        raise SystemExit(0)  # a parseable line was emitted; don't fail the driver


if __name__ == "__main__":
    main()
